"""Evaluator family: positive_negative_pair op parity vs a per-pair
numpy restatement (reference metrics/positive_negative_pair_op.h), and
the v1/v2 evaluator surface (reference trainer_config_helpers/
evaluators.py __all__, python/paddle/v2/evaluator.py generation)."""

import numpy as np

from tests.test_op_tail import run_op


def _pnpair_reference(score, label, query, weight=None):
    n = len(score)
    w = weight if weight is not None else np.ones(n, np.float32)
    pos = neg = neu = 0.0
    for i in range(n):
        for j in range(i + 1, n):
            if query[i] != query[j] or label[i] == label[j]:
                continue
            pw = (w[i] + w[j]) * 0.5
            if score[i] == score[j]:
                neu += pw
            if (score[i] - score[j]) * (label[i] - label[j]) > 0:
                pos += pw
            else:
                neg += pw
    return pos, neg, neu


def test_positive_negative_pair_matches_reference_semantics():
    rng = np.random.RandomState(0)
    n = 40
    score = rng.randint(0, 6, n).astype(np.float32)[:, None]  # forces ties
    label = rng.randint(0, 3, n).astype(np.float32)[:, None]
    query = rng.randint(0, 5, n).astype(np.int64)[:, None]
    out = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": query})
    pos, neg, neu = _pnpair_reference(score[:, 0], label[:, 0], query[:, 0])
    np.testing.assert_allclose(float(np.asarray(out["PositivePair"])), pos)
    np.testing.assert_allclose(float(np.asarray(out["NegativePair"])), neg)
    np.testing.assert_allclose(float(np.asarray(out["NeutralPair"])), neu)


def test_positive_negative_pair_weighted_and_accumulating():
    rng = np.random.RandomState(1)
    n = 16
    score = rng.randn(n).astype(np.float32)[:, None]
    label = rng.randint(0, 2, n).astype(np.float32)[:, None]
    query = rng.randint(0, 3, n).astype(np.int64)[:, None]
    weight = rng.rand(n).astype(np.float32)[:, None]
    out = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": query,
                  "Weight": weight,
                  "AccumulatePositivePair": np.array([10.0], np.float32),
                  "AccumulateNegativePair": np.array([20.0], np.float32),
                  "AccumulateNeutralPair": np.array([30.0], np.float32)})
    pos, neg, neu = _pnpair_reference(score[:, 0], label[:, 0],
                                      query[:, 0], weight[:, 0])
    np.testing.assert_allclose(float(np.asarray(out["PositivePair"])),
                               pos + 10.0, rtol=1e-6)
    np.testing.assert_allclose(float(np.asarray(out["NegativePair"])),
                               neg + 20.0, rtol=1e-6)
    np.testing.assert_allclose(float(np.asarray(out["NeutralPair"])),
                               neu + 30.0, rtol=1e-6)


def test_v1_evaluator_surface_complete():
    """Every reference evaluators.py __all__ name resolves in the v1 DSL
    and its suffix-stripped form in v2 (reference v2/evaluator.py)."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1
    ref_all = [
        "evaluator_base", "classification_error_evaluator",
        "auc_evaluator", "pnpair_evaluator", "precision_recall_evaluator",
        "ctc_error_evaluator", "chunk_evaluator", "sum_evaluator",
        "column_sum_evaluator", "value_printer_evaluator",
        "gradient_printer_evaluator", "maxid_printer_evaluator",
        "maxframe_printer_evaluator", "seqtext_printer_evaluator",
        "classification_error_printer_evaluator",
        "detection_map_evaluator",
    ]
    for n in ref_all:
        assert hasattr(v1, n), "v1 missing %s" % n
    for n in ref_all[1:]:
        assert hasattr(paddle.evaluator, n[:-len("_evaluator")]), n


def test_evaluator_nodes_compute_through_trainer():
    """classification_error + precision_recall + column_sum as
    extra_layers on a trained topology: values fetched via infer match a
    numpy restatement on the same inputs."""
    import paddle_tpu.v2 as paddle

    x = paddle.layer.data(name="ex",
                          type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="ey",
                          type=paddle.data_type.integer_value(3))
    pred = paddle.layer.fc(input=x, size=3,
                           act=paddle.activation.Softmax())
    err = paddle.evaluator.classification_error(input=pred, label=y)
    csum = paddle.evaluator.column_sum(input=pred)

    params = paddle.parameters.create(err)
    rng = np.random.RandomState(2)
    xs = rng.randn(6, 4).astype(np.float32)
    ys = rng.randint(0, 3, (6,)).astype(np.int64)
    got_err, got_sum, got_pred = paddle.infer(
        output_layer=[err, csum, pred], parameters=params,
        input=[(a, b) for a, b in zip(xs, ys)],
        feeding={"ex": 0, "ey": 1})
    p = np.asarray(got_pred)
    want_err = float(np.mean(np.argmax(p, axis=1) != ys))
    np.testing.assert_allclose(float(np.asarray(got_err).ravel()[0]),
                               want_err, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_sum).ravel(), p.sum(0),
                               rtol=1e-5)


def test_pnpair_evaluator_streams_across_batches():
    """The pnpair node accumulates across exe.run calls (persistable
    accumulators), matching the cumulative numpy restatement."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    s = paddle.layer.data(name="ps",
                          type=paddle.data_type.dense_vector(1))
    lb = paddle.layer.data(name="pl",
                           type=paddle.data_type.dense_vector(1))
    q = paddle.layer.data(name="pq",
                          type=paddle.data_type.integer_value(100))
    node = v1.pnpair_evaluator(s, lb, q)
    params = paddle.parameters.create(node)

    rng = np.random.RandomState(3)
    total = np.zeros(3)
    feeds = []
    for _ in range(3):
        n = 10
        sc = rng.randint(0, 4, (n, 1)).astype(np.float32)
        la = rng.randint(0, 2, (n, 1)).astype(np.float32)
        qu = rng.randint(0, 3, (n,)).astype(np.int64)
        feeds.append((sc, la, qu))
        pos, neg, neu = _pnpair_reference(sc[:, 0], la[:, 0], qu)
        total += [pos, neg, neu]

    # one Inference machine keeps one scope -> accumulators persist
    from paddle_tpu.v2.inference import Inference
    inf = Inference(output_layer=node, parameters=params)
    last = None
    for sc, la, qu in feeds:
        last = inf.infer(input=[(sc, la, qu)],
                         feeding={"ps": 0, "pl": 1, "pq": 2})
    np.testing.assert_allclose(np.asarray(last).ravel(), total, rtol=1e-6)


def test_print_grad_dumps_cotangent(capfd):
    """print_phase='backward' prints the incoming gradient (registered
    print_grad lowering), not the forward value (reference print_op.cc)."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        h = fluid.layers.fc(x, size=4, bias_attr=False)
        tapped = fluid.layers.Print(h, message="gradtap",
                                    print_phase="backward")
        loss = fluid.layers.mean(tapped)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
                fetch_list=[loss])
    out = capfd.readouterr().out
    assert "gradtap @GRAD" in out
    # mean over 2x4 -> each cotangent element is 1/8
    assert "0.125" in out
    # no forward-phase print of the raw activations
    assert out.count("gradtap") == 1


def test_ctc_error_evaluator_decodes_frames():
    """Float frame scores are greedy-decoded (merge repeats, drop blank)
    before edit distance — feeding probabilities straight to
    edit_distance would compare garbage integer casts."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    C = 4  # classes incl. blank (= C-1)
    frames = paddle.layer.data(
        name="cf", type=paddle.data_type.dense_vector_sequence(C))
    lab = paddle.layer.data(
        name="cl", type=paddle.data_type.integer_value_sequence(C))
    node = v1.ctc_error_evaluator(input=frames, label=lab)
    params = paddle.parameters.create(node)

    # frames argmax: [0, 0, blank, 1] -> decoded [0, 1]; label [0, 1]
    f = np.full((4, C), 0.1, np.float32)
    f[0, 0] = f[1, 0] = f[2, C - 1] = f[3, 1] = 0.9
    got = paddle.infer(output_layer=node, parameters=params,
                       input=[(f, np.array([0, 1], np.int64))],
                       feeding={"cf": 0, "cl": 1})
    assert float(np.asarray(got).ravel()[0]) == 0.0


def test_detection_map_evaluator_streams_across_batches():
    """Accumulator states are persistable: after a perfect batch and an
    all-wrong batch through ONE Inference machine, the reported mAP is
    cumulative (between the two per-batch values), not the last batch's."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    det = paddle.layer.data(name="dd",
                            type=paddle.data_type.dense_vector_sequence(6))
    gt = paddle.layer.data(name="dg",
                           type=paddle.data_type.dense_vector_sequence(6))
    node = v1.detection_map_evaluator(input=det, label=gt,
                                      overlap_threshold=0.5,
                                      ap_type="integral")
    params = paddle.parameters.create(node)
    from paddle_tpu.v2.inference import Inference
    inf = Inference(output_layer=node, parameters=params)

    box = [0.1, 0.1, 0.4, 0.4]
    gt_row = [[1.0, 0.0] + box]                       # class 1, easy
    perfect = [[1.0, 0.9] + box]                      # hits it
    wrong = [[1.0, 0.9, 0.6, 0.6, 0.9, 0.9]]         # misses it
    m1 = float(np.asarray(inf.infer(
        input=[(np.array(perfect, np.float32),
                np.array(gt_row, np.float32))],
        feeding={"dd": 0, "dg": 1})).ravel()[0])
    m2 = float(np.asarray(inf.infer(
        input=[(np.array(wrong, np.float32),
                np.array(gt_row, np.float32))],
        feeding={"dd": 0, "dg": 1})).ravel()[0])
    assert m1 == 1.0, m1
    # cumulative: 1 TP + 1 FP over 2 positives -> strictly between the
    # perfect 1.0 and the all-wrong 0.0 of batch 2 alone
    assert 0.0 < m2 < 1.0, m2
