"""Static resource & cost analysis (ANALYSIS.md "Resource analysis").

Pins the analyzer's contracts: liveness-based memory planning on a
hand-built program (exact bytes), golden ResourceReports across all 7
zoo models (deterministic — static shapes in, bytes out), dtype-honest
byte accounting (the int8 twin reads <= 0.5x its fp32 artifact
statically), decode KV-cache bytes scaling with the slot table, the
FLOP formula table on the contraction class, the serving admission fit
check (typed rejection BEFORE any build/warm work), and the
est_peak_mb / est_flops exposure through describe()/stats/Prometheus.
"""

import json
import math
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.analysis import (ResourceFitError, ResourceReport,
                                 analyze_artifact, analyze_program,
                                 check_fit, device_peaks)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_mem_flag():
    yield
    fluid.set_flags({"serving_device_mem_mb": 0})


# ---------------------------------------------------------------------------
# byte accounting primitives
# ---------------------------------------------------------------------------

def test_var_nbytes_hint_dtypes():
    from paddle_tpu.fluid import core as fcore
    p = Program()
    blk = p.global_block()
    f32 = blk.create_var(name="f", shape=[-1, 8], dtype="float32")
    i8 = blk.create_var(name="q", shape=[16, 4], dtype="int8")
    assert f32.numel_hint(batch=4) == 32
    assert f32.nbytes_hint(batch=4) == 128
    assert i8.nbytes_hint() == 64            # one byte per int8 element
    assert fcore.dtype_size("bfloat16") == 2
    assert fcore.dtype_size(np.float64) == 8


def test_liveness_memory_plan_exact_bytes():
    # x[4,8] -> relu -> h -> relu -> y ; w persistable [4,8].
    # params pinned whole-program; at op 1 both h and y are live along
    # with the still-live feed x => peak = 3*128 activations + 128 param
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    blk.create_var(name="w", shape=[4, 8], dtype="float32",
                   persistable=True)
    blk.create_var(name="h", shape=[4, 8], dtype="float32")
    blk.create_var(name="y", shape=[4, 8], dtype="float32")
    blk.append_op(type="relu", inputs={"X": ["x"]},
                  outputs={"Out": ["h"]}, infer_shape=False)
    blk.append_op(type="elementwise_add",
                  inputs={"X": ["h"], "Y": ["x"]},
                  outputs={"Out": ["y"]}, infer_shape=False)
    rep = analyze_program(p, feeds=["x"], fetches=["y"])
    assert rep.param_bytes == 128
    assert rep.activation_peak_bytes == 3 * 128
    assert rep.peak_bytes == 4 * 128
    assert rep.n_ops == 2
    kinds = {r["var"]: r["kind"] for r in rep.top_contributors}
    assert kinds["w"] == "param" and kinds["x"] == "feed"
    assert kinds["h"] == "activation"
    # wire-encodable report
    json.dumps(rep.to_dict())


def test_cost_model_mul_exact_flops():
    # X [3, 16] x Y [16, 5] => 2*3*16*5 FLOPs
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[3, 16], dtype="float32",
                   is_data=True)
    blk.create_var(name="w", shape=[16, 5], dtype="float32",
                   persistable=True)
    blk.create_var(name="o", shape=[3, 5], dtype="float32")
    blk.append_op(type="mul", inputs={"X": ["x"], "Y": ["w"]},
                  outputs={"Out": ["o"]}, infer_shape=False)
    rep = analyze_program(p, feeds=["x"], fetches=["o"])
    assert rep.total_flops == 2 * 3 * 16 * 5
    # bytes: x + w + o, fp32
    assert rep.total_bytes == (3 * 16 + 16 * 5 + 3 * 5) * 4
    assert rep.arithmetic_intensity == pytest.approx(
        rep.total_flops / rep.total_bytes)


def test_loop_resident_sub_block_counts_at_owning_op():
    # a while body's locals are loop-resident: they appear in the
    # timeline at the owning op's index
    p = Program()
    blk = p.global_block()
    blk.create_var(name="cond", shape=[1], dtype="bool", is_data=True)
    sub = p._create_block()
    sub.create_var(name="body_tmp", shape=[256], dtype="float32")
    sub.append_op(type="relu", inputs={"X": ["body_tmp"]},
                  outputs={"Out": ["body_tmp"]}, infer_shape=False)
    p._rollback()
    blk.append_op(type="while", inputs={"Cond": ["cond"]}, outputs={},
                  attrs={"sub_block": sub}, infer_shape=False)
    rep = analyze_program(p, feeds=["cond"])
    assert rep.activation_peak_bytes >= 256 * 4
    assert any(r["var"] == "body_tmp" and r["kind"] == "loop"
               for r in rep.top_contributors)


# ---------------------------------------------------------------------------
# golden reports across the zoo (deterministic: static shapes in,
# bytes out — the pins survive anything but a real model/cost change)
# ---------------------------------------------------------------------------

_GOLDEN = {
    # name: (param_bytes, peak_bytes, total_flops) — deterministic:
    # static shapes in, bytes out; regenerate with the snippet in
    # ANALYSIS.md if the models or the cost table legitimately change
    "mnist": (403012, 2403868, 91758004),
    "vgg": (183093596, 260421164, 7609255116),
    "resnet": (2186068, 8511060, 502292496),
    "se_resnext": (204523988, 329752792, 4323793326),
    "transformer": (6927596, 14710896, 226760507),
    "stacked_dynamic_lstm": (2286500, 3049172, 738182),
    "machine_translation": (680756, 909736, 441195),
}


@pytest.mark.parametrize("name", sorted(_GOLDEN))
def test_zoo_golden_resource_reports(name):
    import importlib
    import sys
    sys.path.insert(0, REPO)
    from tools.lint_program import ZOO, _name
    spec = next(z for z in ZOO if z[0] == name)
    _, mod, kw = spec
    m = importlib.import_module(mod)
    main, _startup, feeds, loss, acc, predict = m.get_model(**kw)
    fetches = [_name(v) for v in (loss, acc, predict) if v is not None]
    rep = analyze_program(main, feeds=[_name(f) for f in feeds],
                          fetches=fetches,
                          batch=kw.get("batch_size", 1))
    want_params, want_peak, want_flops = _GOLDEN[name]
    assert math.isclose(rep.param_bytes, want_params, rel_tol=0.02), \
        (name, rep.param_bytes)
    assert math.isclose(rep.peak_bytes, want_peak, rel_tol=0.05), \
        (name, rep.peak_bytes)
    assert math.isclose(rep.total_flops, want_flops, rel_tol=0.05), \
        (name, rep.total_flops)
    assert rep.peak_bytes > rep.param_bytes       # activations exist
    assert rep.precision == "fp32"


# ---------------------------------------------------------------------------
# artifacts: est-vs-actual, the quantized twin, decode KV scaling
# ---------------------------------------------------------------------------

def _export_fc(tmp_path, name="m", in_dim=64, hid=64):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[in_dim], dtype="float32")
        h = fluid.layers.fc(input=x, size=hid, act="relu")
        pred = fluid.layers.fc(input=h, size=8, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md


def test_artifact_est_matches_actual_bytes(tmp_path):
    md = _export_fc(tmp_path)
    rep = analyze_artifact(md, batch=4)
    assert rep.actual_param_bytes is not None
    assert math.isclose(rep.param_bytes, rep.actual_param_bytes,
                        rel_tol=0.10)          # the acceptance bound
    assert rep.what == md and rep.batch == 4


def test_quantized_twin_static_footprint(tmp_path):
    from paddle_tpu.inference.quantize import quantize_inference_model
    md = _export_fc(tmp_path, in_dim=64, hid=64)
    q = quantize_inference_model(md, str(tmp_path / "m_int8"),
                                 min_weight_elems=1024)
    fp = analyze_artifact(md)
    qr = analyze_artifact(q["dst"])
    assert qr.precision == "int8" and fp.precision == "fp32"
    # the int8 lane's weight footprint reads statically: the 64x64 and
    # 64x8 weights drop to 1 byte/elem (+ fp32 scale rows)
    assert qr.param_bytes <= 0.5 * fp.param_bytes
    # and the estimate still matches the actual committed payloads
    assert math.isclose(qr.param_bytes, qr.actual_param_bytes,
                        rel_tol=0.10)


def test_decode_kv_bytes_scale_with_slots(tmp_path):
    from paddle_tpu.inference.decode import (GenerativePredictor,
                                             build_tiny_decode_model)
    d = str(tmp_path / "dec")
    build_tiny_decode_model(d, vocab_size=32, d_model=16, n_heads=2,
                            n_layers=2, max_seq_len=64)
    r4 = analyze_artifact(d, decode_slots=4)
    r8 = analyze_artifact(d, decode_slots=8)
    # K and V, [L, slots, S, H, Dh] fp32
    assert r4.kv_cache_bytes == 2 * 2 * 4 * 64 * 2 * 8 * 4
    assert r8.kv_cache_bytes == 2 * r4.kv_cache_bytes
    assert r8.peak_bytes > r4.peak_bytes
    assert r4.param_bytes == r4.actual_param_bytes
    assert r4.param_bytes > 0
    # the predictor's own accounting hooks agree with the analyzer
    g = GenerativePredictor(d)
    assert g.kv_cache_bytes(4) == r4.kv_cache_bytes
    assert g.param_bytes() == r4.param_bytes


def test_predictor_resource_report_post_transpile(tmp_path):
    from paddle_tpu.inference import AnalysisConfig, Predictor
    md = _export_fc(tmp_path)
    cfg = AnalysisConfig(model_dir=md)
    cfg.batch_size_buckets = (2, 8)
    p = Predictor(cfg)
    rep = p.resource_report()
    assert rep.batch == 8            # defaults to the largest bucket
    assert rep.peak_bytes > rep.param_bytes > 0
    assert rep.precision == "fp32"


# ---------------------------------------------------------------------------
# serving admission
# ---------------------------------------------------------------------------

def _export_big_fc(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[256], dtype="float32")
        h = fluid.layers.fc(input=x, size=2048, act="relu")
        pred = fluid.layers.fc(input=h, size=64, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / "big")
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md


def test_load_model_rejects_unfittable_before_build(tmp_path):
    from paddle_tpu import compile_cache
    from paddle_tpu.serving import ModelRegistry
    md = _export_big_fc(tmp_path)        # ~2.2 MiB of weights
    reg = ModelRegistry()
    fluid.set_flags({"serving_device_mem_mb": 2})
    cc_before = compile_cache.stats()
    with pytest.raises(ResourceFitError) as ei:
        reg.load_model("big", md)
    e = ei.value
    # the typed error names both sides of the comparison
    assert e.estimated_bytes > e.available_bytes
    assert e.available_bytes == 2 << 20
    assert str(e.estimated_bytes) in str(e)
    assert str(e.available_bytes) in str(e)
    # rejected BEFORE any build/warm work: no model entry, no compile
    assert reg.model_names() == []
    assert compile_cache.stats() == cc_before


def test_load_model_fit_ok_exposes_estimates(tmp_path):
    from paddle_tpu.serving import ModelRegistry
    md = _export_big_fc(tmp_path)
    reg = ModelRegistry()
    fluid.set_flags({"serving_device_mem_mb": 64})
    try:
        entry = reg.load_model("big", md, warm=False)
        assert entry.resource is not None
        assert entry.resource.peak_bytes > 0
        info = reg.describe()["big"]
        assert info["est_peak_mb"] == pytest.approx(
            entry.resource.peak_mb, abs=1e-3)
        assert info["est_flops"] == entry.resource.total_flops
        snap = reg.metrics.model("big").snapshot()
        assert snap["est_peak_mb"] == pytest.approx(
            entry.resource.peak_mb, abs=1e-3)
        assert snap["est_flops"] == entry.resource.total_flops
        from paddle_tpu.obs.registry import MetricsRegistry
        mreg = MetricsRegistry()
        mreg.attach_serving(reg.metrics)
        text = mreg.prometheus_text()
        assert 'paddle_tpu_model_est_peak_mb{model="big"}' in text
        assert 'paddle_tpu_model_est_flops{model="big"}' in text
    finally:
        reg.close_all(drain=False)


def test_fit_check_emits_rejected_event(tmp_path):
    from paddle_tpu.obs import events as obs_events
    from paddle_tpu.serving import ModelRegistry
    md = _export_big_fc(tmp_path)
    reg = ModelRegistry()
    fluid.set_flags({"serving_device_mem_mb": 1})
    with pytest.raises(ResourceFitError):
        reg.load_model("nofit", md)
    evs = [e for e in obs_events.recent_events(kind="model_fit_rejected")
           if e.get("model") == "nofit"]
    assert evs and evs[-1]["est_bytes"] > evs[-1]["available_bytes"]


def test_check_fit_no_budget_passes(tmp_path):
    # CPU + flag 0: no known budget -> trivially fits (avail None)
    rep = ResourceReport(what="x")
    rep.param_bytes = 10 << 30
    est, avail = check_fit(rep)
    assert est == rep.peak_bytes
    assert avail is None or avail > 0   # TPU hosts resolve a real cap


def test_device_peaks_table():
    peaks = device_peaks(None)
    assert peaks["peak_flops"] > 0 and peaks["hbm_bytes_per_s"] > 0
    # the roofline denominator rides the report
    rep = ResourceReport()
    assert rep.est_step_ms >= 0.0 and 0.0 <= rep.mfu_cap() <= 1.0


# ---------------------------------------------------------------------------
# debugger cost columns (satellite)
# ---------------------------------------------------------------------------

def test_debugger_renders_cost_columns(tmp_path):
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[3, 16], dtype="float32",
                   is_data=True)
    blk.create_var(name="w", shape=[16, 5], dtype="float32",
                   persistable=True)
    blk.create_var(name="o", shape=[3, 5], dtype="float32")
    blk.append_op(type="mul", inputs={"X": ["x"], "Y": ["w"]},
                  outputs={"Out": ["o"]}, infer_shape=False)
    rep = analyze_program(p, feeds=["x"], fetches=["o"])
    txt = fluid.debugger.pprint_program_codes(p, costs=rep)
    assert "est_flops=" in txt and "est_bytes=" in txt
    # report hook the columns ride
    assert rep.op_cost(0, 0) == (480, 572)    # 2*3*16*5 F, 143 elems
    dot = fluid.debugger.draw_block_graphviz(
        blk, path=str(tmp_path / "g.dot"), costs=rep)
    assert "480F" in dot and "572B" in dot
    # without costs the old contract holds
    bare = fluid.debugger.pprint_program_codes(p)
    assert "est_flops" not in bare
