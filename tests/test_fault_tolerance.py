"""Fault-tolerant training runtime: checkpoint vault atomicity/CRC,
anomaly sentinel policies, step watchdog, retry wrappers, and the chaos
harness's end-to-end recovery scenarios (ISSUE 2; reference analogues:
go/pserver/service.go CRC checkpoints, go/master lease recovery,
FLAGS_check_nan_inf, TF checkpoint fault tolerance arXiv:1605.08695)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import checkpoint as ckpt
from paddle_tpu.fluid import io as fluid_io
from paddle_tpu.fluid import sentinel as sentinel_mod
from paddle_tpu.utils.retry import RetryPolicy
import paddle_tpu.reader as rd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import chaos  # noqa: E402  (tools/chaos.py — the fault-injection harness)


# ---------------------------------------------------------------------------
# vault: layout, meta schema, rotation
# ---------------------------------------------------------------------------

def _build_net():
    def train_func():
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))

    def optimizer_func():
        return fluid.optimizer.SGD(learning_rate=0.05)

    return train_func, optimizer_func


def _small_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def test_checkpoint_meta_roundtrip_int_step(tmp_path):
    """Satellite 1: save_checkpoint(step=<int>) used to write meta the
    Trainer crashed on (meta.get on an int).  Both sides now speak one
    {"epoch", "step"} schema."""
    root = str(tmp_path / "vault")
    with fluid.scope_guard(fluid.Scope()):
        main, startup, _ = _small_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        written = fluid_io.save_checkpoint(exe, root, main_program=main,
                                           step=7)
        assert written == {"epoch": 0, "step": 7}
        meta = fluid_io.load_checkpoint(exe, root, main_program=main)
    assert isinstance(meta, dict)
    assert int(meta.get("epoch", 0)) == 0 and int(meta.get("step")) == 7


def test_checkpoint_meta_legacy_layout(tmp_path):
    """Pre-vault flat checkpoints (npz + __meta__.json with an int or a
    dict under 'step') still load, normalized to the canonical schema."""
    d = str(tmp_path)
    with fluid.scope_guard(fluid.Scope()):
        main, startup, _ = _small_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid_io.save_persistables(exe, d, main,
                                   filename="__checkpoint__.npz")
        with open(os.path.join(d, "__meta__.json"), "w") as f:
            json.dump({"step": 5}, f)
        assert fluid_io.load_checkpoint(exe, d, main) == \
            {"epoch": 0, "step": 5}
        with open(os.path.join(d, "__meta__.json"), "w") as f:
            json.dump({"step": {"epoch": 1, "step": 9}}, f)
        meta = fluid_io.load_checkpoint(exe, d, main)
    assert meta["epoch"] == 1 and meta["step"] == 9


def test_vault_rotation_and_latest(tmp_path):
    root = str(tmp_path)
    arrays = {"w": np.arange(4, dtype=np.float32)}
    for s in range(1, 6):
        ckpt.save_checkpoint_dir(root, arrays, {"epoch": 0, "step": s},
                                 max_num_checkpoints=2)
    steps = [s for s, _ in ckpt.list_checkpoints(root)]
    assert steps == [4, 5], "keep-N rotation broke: %s" % steps
    assert ckpt.latest_checkpoint(root).endswith("checkpoint_5")
    with open(os.path.join(root, ckpt.LATEST_NAME)) as f:
        assert f.read().strip() == "checkpoint_5"


def test_empty_dir_raises_filenotfound(tmp_path):
    with fluid.scope_guard(fluid.Scope()):
        main, startup, _ = _small_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with pytest.raises(FileNotFoundError):
            fluid_io.load_checkpoint(exe, str(tmp_path), main)


# ---------------------------------------------------------------------------
# vault: corruption + crash atomicity
# ---------------------------------------------------------------------------

def test_bit_flip_rejected_naming_array(tmp_path):
    root = str(tmp_path)
    arrays = {"fc_w": np.arange(24, dtype=np.float32).reshape(4, 6),
              "fc_b": np.ones(6, np.float32)}
    path = ckpt.save_checkpoint_dir(root, arrays, {"epoch": 0, "step": 1})
    chaos.corrupt_array(path, "fc_w")
    with pytest.raises(ckpt.CheckpointCorruptionError, match="fc_w"):
        ckpt.load_checkpoint_dir(path)
    # the sibling array alone still verifies — corruption is per-shard
    arrays2, _ = ckpt.load_checkpoint_dir(path, names={"fc_b"})
    np.testing.assert_array_equal(arrays2["fc_b"], arrays["fc_b"])


class _Interrupt(BaseException):
    """In-process stand-in for a crash at an exact protocol point."""


@pytest.mark.parametrize("point", ["array_written", "arrays_written",
                                   "manifest_written"])
def test_interrupted_save_keeps_last_good(tmp_path, point):
    """A save dying at any pre-commit point must leave `latest` naming
    the previous fully-committed checkpoint, and the next save must
    sweep the in-flight temp dir."""
    root = str(tmp_path)
    arrays = {"w": np.arange(8, dtype=np.float32),
              "b": np.ones(3, np.float32)}
    ckpt.save_checkpoint_dir(root, arrays, {"epoch": 0, "step": 1})

    def boom(p):
        if p == point:
            raise _Interrupt(p)

    ckpt.set_chaos_hook(boom)
    try:
        with pytest.raises(_Interrupt):
            ckpt.save_checkpoint_dir(root, arrays,
                                     {"epoch": 0, "step": 2})
    finally:
        ckpt.set_chaos_hook(None)
    latest = ckpt.latest_checkpoint(root)
    assert latest.endswith("checkpoint_1")
    ckpt.verify_checkpoint_dir(latest)
    assert any(n.startswith("_tmp.checkpoint_")
               for n in os.listdir(root)), "no in-flight temp left behind"
    # the next save commits AND sweeps the stale temp
    ckpt.save_checkpoint_dir(root, arrays, {"epoch": 0, "step": 3})
    assert not any(n.startswith("_tmp.checkpoint_")
                   for n in os.listdir(root))
    assert ckpt.latest_checkpoint(root).endswith("checkpoint_3")


def test_kill9_mid_save_subprocess(tmp_path):
    """Acceptance: a real SIGKILL delivered while a training child is
    paused inside the commit protocol leaves a loadable, CRC-verified
    last-good checkpoint."""
    meta = chaos.scenario_crash_save(str(tmp_path / "crash"),
                                     point="manifest_written",
                                     crash_at_save=2, real_kill=True,
                                     steps=4, verbose=False)
    assert meta["step"] == 1


def test_async_save_commits_and_reports_errors(tmp_path):
    root = str(tmp_path / "vault")
    saver = ckpt.AsyncCheckpointSaver()
    arrays = {"w": np.arange(4, dtype=np.float32)}
    for s in (1, 2, 3):
        saver.submit(root, arrays, {"epoch": 0, "step": s},
                     max_num_checkpoints=2)
    saver.wait(timeout=30)
    assert [s for s, _ in ckpt.list_checkpoints(root)] == [2, 3]
    # error path: the vault root is a FILE -> the background save fails
    # and the failure surfaces on wait(), not silently
    bad_root = str(tmp_path / "not_a_dir")
    with open(bad_root, "w") as f:
        f.write("x")
    saver.submit(bad_root, arrays, {"epoch": 0, "step": 9})
    with pytest.raises(ckpt.CheckpointError):
        saver.wait(timeout=30)


# ---------------------------------------------------------------------------
# Trainer: resume trajectory parity
# ---------------------------------------------------------------------------

def _run_trainer(ckpt_dir, num_epochs, data, stop_after=None,
                 step_interval=1):
    """Train the tiny regression net in a FRESH scope; returns the final
    persistable arrays (and implicitly exercises checkpoint resume when
    ckpt_dir already holds a vault)."""
    train_func, optimizer_func = _build_net()

    def reader():
        for x, y in data:
            yield [(x, y)]

    with fluid.scope_guard(fluid.Scope()) as scope:
        cfg = None
        if ckpt_dir is not None:
            cfg = fluid.contrib.CheckpointConfig(
                checkpoint_dir=ckpt_dir, step_interval=step_interval)
        trainer = fluid.contrib.Trainer(train_func, optimizer_func,
                                        place=fluid.CPUPlace(),
                                        checkpoint_config=cfg)
        seen = {"steps": 0}

        def handler(ev):
            if isinstance(ev, fluid.contrib.EndStepEvent):
                seen["steps"] += 1
                if stop_after is not None and seen["steps"] >= stop_after:
                    trainer.stop()

        trainer.train(num_epochs=num_epochs, event_handler=handler,
                      reader=reader, feed_order=["x", "y"])
        from paddle_tpu.fluid import functionalizer
        names = functionalizer.persistable_names(trainer.train_program)
        return {n: np.asarray(scope.get(n)) for n in names
                if scope.get(n) is not None}


def test_trainer_resume_reproduces_trajectory(tmp_path):
    """Acceptance: resume from last-good reproduces the uninterrupted
    run exactly — including a mid-epoch interruption (epoch_step in the
    meta + deterministic reader replay) and a crash-interrupted save
    sitting in the vault as a stale temp dir."""
    rng = np.random.RandomState(0)
    data = [(x, np.array([x.sum()], np.float32))
            for x in [rng.randn(4).astype(np.float32) for _ in range(5)]]

    baseline = _run_trainer(None, num_epochs=2, data=data)

    vault = str(tmp_path / "vault")
    interrupted = _run_trainer(vault, num_epochs=2, data=data,
                               stop_after=7)
    assert interrupted is not None  # 7 of 10 steps ran, ckpt at step 7

    # simulate a save killed mid-commit before the process died: the
    # vault must keep serving checkpoint_7 around the stale temp
    def boom(p):
        if p == "manifest_written":
            raise _Interrupt(p)
    ckpt.set_chaos_hook(boom)
    try:
        with pytest.raises(_Interrupt):
            ckpt.save_checkpoint_dir(
                vault, {"junk": np.zeros(2, np.float32)},
                {"epoch": 9, "step": 999})
    finally:
        ckpt.set_chaos_hook(None)

    meta = ckpt.load_checkpoint_dir(ckpt.latest_checkpoint(vault))[1]
    assert meta["step"] == 7 and meta["epoch"] == 1 and \
        meta["epoch_step"] == 2, meta

    resumed = _run_trainer(vault, num_epochs=2, data=data)
    assert set(resumed) == set(baseline)
    for n in baseline:
        np.testing.assert_array_equal(
            resumed[n], baseline[n],
            err_msg="param %r diverged after resume" % n)


# ---------------------------------------------------------------------------
# sentinel
# ---------------------------------------------------------------------------

def test_sentinel_state_machine():
    s = sentinel_mod.AnomalySentinel(max_bad_steps=3, policy="skip")
    good = [("loss", np.float32(1.0))]
    bad = [("loss", np.float32(np.nan))]
    assert s.observe(good) == sentinel_mod.OK
    assert s.observe(bad) == sentinel_mod.SKIP
    assert s.observe(bad) == sentinel_mod.SKIP
    with pytest.raises(sentinel_mod.SentinelError):
        s.observe(bad)           # K-th consecutive, no rollback target
    s2 = sentinel_mod.AnomalySentinel(max_bad_steps=2, policy="rollback")
    assert s2.observe(bad) == sentinel_mod.SKIP
    assert s2.observe(bad) == sentinel_mod.ROLLBACK
    assert s2.observe(good) == sentinel_mod.OK   # recovery resets streak
    assert s2.observe([("loss", np.float32(np.inf))]) == sentinel_mod.SKIP
    assert s2.observe(bad) == sentinel_mod.ROLLBACK
    with pytest.raises(sentinel_mod.SentinelError):
        for _ in range(4):       # still diverging after rollback: give up
            s2.observe(bad)


def test_sentinel_nan_poison_skip_then_rollback():
    """Chaos scenario end-to-end: poisoned batches are reverted, K
    consecutive poisoned steps roll back to the last-good checkpoint."""
    chaos.scenario_nan_poison(verbose=False)


def test_sentinel_skip_policy_raises_without_checkpoint():
    rng = np.random.RandomState(1)
    data = [(x, np.array([x.sum()], np.float32))
            for x in [rng.randn(4).astype(np.float32) for _ in range(8)]]

    def reader():
        for x, y in data:
            yield [(x, y)]

    poisoned = chaos.nan_poison_reader(reader, poison_steps={2, 3, 4})
    train_func, optimizer_func = _build_net()
    fluid.set_flags({"sentinel_nan_check": True,
                     "sentinel_policy": "skip",
                     "sentinel_max_bad_steps": 2})
    try:
        with fluid.scope_guard(fluid.Scope()):
            trainer = fluid.contrib.Trainer(train_func, optimizer_func,
                                            place=fluid.CPUPlace())
            with pytest.warns(UserWarning, match="reverted"):
                with pytest.raises(sentinel_mod.SentinelError):
                    trainer.train(num_epochs=1,
                                  event_handler=lambda ev: None,
                                  reader=poisoned, feed_order=["x", "y"])
    finally:
        fluid.set_flags({"sentinel_nan_check": False,
                         "sentinel_policy": "skip",
                         "sentinel_max_bad_steps": 3})


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_raises_on_hung_step():
    from paddle_tpu.fluid.executor import _watchdog_call, \
        StepWatchdogTimeout
    t0 = time.monotonic()
    with pytest.raises(StepWatchdogTimeout):
        _watchdog_call(lambda: time.sleep(10), 0.2, "wedged step")
    assert time.monotonic() - t0 < 5.0, "watchdog did not give up"
    assert _watchdog_call(lambda: 42, 5.0) == 42
    with pytest.raises(ValueError):   # worker errors propagate verbatim
        _watchdog_call(lambda: (_ for _ in ()).throw(ValueError("x")),
                       5.0)


def test_watchdog_executor_step_passes_under_budget():
    fluid.set_flags({"step_watchdog_secs": 60.0})
    try:
        with fluid.scope_guard(fluid.Scope()):
            main, startup, loss = _small_program()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            xs = np.ones((4, 4), np.float32)
            ys = xs.sum(axis=1, keepdims=True)
            (l,) = exe.run(main, feed={"x": xs, "y": ys},
                           fetch_list=[loss])
            assert np.isfinite(np.asarray(l)).all()
    finally:
        fluid.set_flags({"step_watchdog_secs": 0.0})


# ---------------------------------------------------------------------------
# retry policy + hardened wrappers
# ---------------------------------------------------------------------------

def test_retry_policy_delays_and_call():
    p = RetryPolicy(max_attempts=4, base_delay=0.1, max_delay=10.0,
                    jitter=0.0, sleep=lambda d: None)
    assert [round(d, 3) for d in p.delays()] == [0.1, 0.2, 0.4]
    pj = RetryPolicy(max_attempts=50, base_delay=0.1, max_delay=0.1,
                     jitter=0.5, sleep=lambda d: None)
    ds = list(pj.delays())
    assert all(0.05 <= d <= 0.15 for d in ds) and len(set(ds)) > 1

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "done"

    assert RetryPolicy(max_attempts=5, sleep=lambda d: None).call(flaky) \
        == "done"
    assert calls["n"] == 3
    with pytest.raises(OSError):
        RetryPolicy(max_attempts=2, sleep=lambda d: None).call(
            lambda: (_ for _ in ()).throw(OSError("always")))
    # a past deadline stops retrying immediately
    with pytest.raises(OSError):
        RetryPolicy(max_attempts=100, sleep=lambda d: None).call(
            lambda: (_ for _ in ()).throw(OSError("always")),
            deadline=time.monotonic() - 1.0)


def test_retry_reader_resumes_epoch():
    attempts = {"n": 0}

    def flaky_reader():
        attempts["n"] += 1
        fail_this = attempts["n"] == 1

        def it():
            for i in range(10):
                if fail_this and i == 5:
                    raise OSError("stream broke")
                yield i
        return it()

    policy = RetryPolicy(max_attempts=3, retry_on=(OSError,),
                         sleep=lambda d: None)
    got = list(rd.retry_reader(flaky_reader, policy=policy)())
    assert got == list(range(10)), got   # no loss, no duplicates
    assert attempts["n"] == 2

    def always_broken():
        def it():
            yield 0
            raise OSError("dead source")
        return it()

    with pytest.raises(OSError):
        list(rd.retry_reader(always_broken, policy=RetryPolicy(
            max_attempts=2, retry_on=(OSError,), sleep=lambda d: None))())


def test_wait_server_ready_times_out_fast():
    from paddle_tpu.distributed.rpc import wait_server_ready
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here anymore
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        wait_server_ready(["127.0.0.1:%d" % port], timeout=0.4)
    assert time.monotonic() - t0 < 5.0


def test_master_client_survives_dropped_connection():
    chaos.scenario_drop_rpc(verbose=False)


# ---------------------------------------------------------------------------
# reader worker death (satellite 3)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("order", [False, True])
def test_xmap_mapper_death_surfaces(order):
    def src():
        return iter(range(30))

    def mapper(x):
        if x == 7:
            raise ValueError("mapper died on sample 7")
        return x * 2

    r = rd.xmap_readers(mapper, src, 4, 8, order=order)
    with pytest.raises(rd.ReaderWorkerFailed, match="sample 7"):
        list(r())


def test_xmap_source_death_surfaces():
    def bad_src():
        def it():
            yield 1
            yield 2
            raise RuntimeError("source reader died")
        return it()

    r = rd.xmap_readers(lambda x: x, bad_src, 2, 4)
    with pytest.raises(rd.ReaderWorkerFailed, match="source reader died"):
        list(r())


@pytest.mark.parametrize("use_pipe", [True, False])
def test_multiprocess_reader_child_exception(use_pipe):
    def good():
        return iter([1, 2, 3])

    def bad():
        def it():
            yield 10
            raise ValueError("child reader exploded")
        return it()

    r = rd.multiprocess_reader([good, bad], use_pipe=use_pipe)
    with pytest.raises(rd.ReaderWorkerFailed, match="exploded"):
        list(r())


def test_multiprocess_reader_child_killed():
    """A hard child death (SIGKILL — no exception, no sentinel) must
    raise, not silently truncate the epoch (the old behavior)."""
    def victim():
        def it():
            yield 1
            os.kill(os.getpid(), signal.SIGKILL)
            yield 2  # pragma: no cover
        return it()

    r = rd.multiprocess_reader([victim], use_pipe=True)
    with pytest.raises(rd.ReaderWorkerFailed, match="died before"):
        list(r())


# ---------------------------------------------------------------------------
# tools: verify_checkpoint CLI + chaos --smoke (satellite 5)
# ---------------------------------------------------------------------------

def _run_tool(args, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)


def test_verify_checkpoint_cli(tmp_path):
    root = str(tmp_path)
    arrays = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    path = ckpt.save_checkpoint_dir(root, arrays, {"epoch": 2, "step": 11})
    out = _run_tool([os.path.join(REPO, "tools", "verify_checkpoint.py"),
                     root])
    assert out.returncode == 0, out.stderr
    assert "step=11" in out.stdout and "CRC32 verified" in out.stdout
    chaos.bit_flip(os.path.join(path, "w.npy"))
    out = _run_tool([os.path.join(REPO, "tools", "verify_checkpoint.py"),
                     root])
    assert out.returncode == 2
    assert "'w'" in out.stderr and "CRC32" in out.stderr


def test_chaos_smoke_subprocess(tmp_path):
    out = _run_tool([os.path.join(REPO, "tools", "chaos.py"), "--smoke",
                     "--workdir", str(tmp_path)])
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CHAOS SMOKE PASS" in out.stdout
