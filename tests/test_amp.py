"""bf16 automatic mixed precision tests (reference analogue: fp16
data_type_transform + float16.h; TPU-first bf16 design)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program


@pytest.fixture(autouse=True)
def _amp_off_after():
    yield
    fluid.set_amp(False)


def _build_mlp():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=32, act="relu")
        p = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return main, startup, loss


def test_amp_trains_and_keeps_fp32_master_weights():
    rng = np.random.RandomState(0)
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.set_amp(True)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = xs.sum(axis=1, keepdims=True)
    losses = []
    for _ in range(15):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).flatten()[0]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0] * 0.5
    # master weights stayed fp32 in the scope
    scope = fluid.global_scope()
    for p in main.all_parameters():
        arr = scope.get(p.name)
        assert str(np.asarray(arr).dtype) == "float32", p.name


def test_amp_matches_fp32_loosely():
    """bf16 compute tracks the fp32 result within bf16 tolerance."""
    rng = np.random.RandomState(1)
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(8, 16).astype(np.float32)
    ys = xs.sum(axis=1, keepdims=True)
    scope = fluid.global_scope()
    snap = {p.name: np.array(np.asarray(scope.get(p.name)))
            for p in main.all_parameters()}
    (l32,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    # restore the identical initial params for the amp run
    for name, arr in snap.items():
        scope.set(name, arr)
    fluid.set_amp(True)
    (l16,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    l32 = float(np.asarray(l32).flatten()[0])
    l16 = float(np.asarray(l16).flatten()[0])
    assert abs(l32 - l16) / max(abs(l32), 1e-6) < 0.05
