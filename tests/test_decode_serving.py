"""Continuous batching + streaming decode tests (SERVING.md
"Continuous batching & streaming", paddle_tpu/inference/decode.py,
serving DecodeBatcher + infer_stream).

The load-bearing contracts, in rough dependency order:

* the Pallas decode-attention kernel matches the plain-XLA oracle on
  the slot-cache shape (mixed live lengths, empty and full slots);
* greedy token streams are BIT-EXACT between a continuous batch with
  requests of mixed lengths joining and leaving mid-flight and a
  single-request non-batched DecodeSession — per-slot independence is
  exact, not approximate;
* slot recycling: a freed slot is ZEROED before reuse (no cross-request
  KV leakage) and more requests than slots all complete;
* streaming chunk ordering/completeness over the wire under concurrent
  clients; deadline eviction MID-DECODE with a typed error frame;
* prefill-bucket executables ride the persistent compile cache (a
  second load of the same artifact is all hits, zero fresh compiles).

Everything CPU-safe under JAX_PLATFORMS=cpu.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.inference.decode import (DecodeSession,
                                         GenerativePredictor,
                                         build_tiny_decode_model,
                                         greedy_decode)
from paddle_tpu.serving import (DeadlineExceeded, DecodeBatcher,
                                InferenceServer, ServerOverloaded,
                                ServingClient, ServingMetrics,
                                set_dispatch_delay)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clear_chaos():
    yield
    set_dispatch_delay(0.0)


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("decode_model") / "lm")
    build_tiny_decode_model(d, vocab_size=32, d_model=16, n_heads=2,
                            n_layers=2, max_seq_len=64, eos_id=0,
                            seed=7)
    return d


@pytest.fixture(scope="module")
def predictor(artifact):
    return GenerativePredictor(artifact)


# ---------------------------------------------------------------------------
# decode-attention kernel
# ---------------------------------------------------------------------------

class TestDecodeKernel:
    def test_kernel_matches_reference_mixed_lengths(self):
        from paddle_tpu.ops.pallas_kernels import (
            decode_attention, decode_attention_reference)
        rng = np.random.RandomState(3)
        N, S, H, D = 5, 32, 2, 8
        q = rng.randn(N, H, D).astype(np.float32)
        k = rng.randn(N, S, H, D).astype(np.float32)
        v = rng.randn(N, S, H, D).astype(np.float32)
        lengths = np.array([1, 7, 32, 13, 2], np.int32)
        ref = np.asarray(decode_attention_reference(q, k, v, lengths))
        for bkv in (8, 16, 32):
            out = np.asarray(decode_attention(q, k, v, lengths,
                                              block_kv=bkv))
            np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_empty_slot_is_welldefined_and_isolated(self):
        """A length-0 (dead) slot must not disturb live slots' rows."""
        from paddle_tpu.ops.pallas_kernels import decode_attention
        rng = np.random.RandomState(4)
        N, S, H, D = 3, 16, 2, 8
        q = rng.randn(N, H, D).astype(np.float32)
        k = rng.randn(N, S, H, D).astype(np.float32)
        v = rng.randn(N, S, H, D).astype(np.float32)
        live = np.asarray(decode_attention(
            q, k, v, np.array([5, 9, 16], np.int32), block_kv=8))
        mixed = np.asarray(decode_attention(
            q, k, v, np.array([5, 0, 16], np.int32), block_kv=8))
        assert np.array_equal(live[0], mixed[0])
        assert np.array_equal(live[2], mixed[2])
        assert np.all(np.isfinite(mixed[1]))

    def test_block_config_resolution_and_tuning_record(self, tmp_path):
        from paddle_tpu.ops import attention_tuning as at
        old = fluid.get_flags(["flash_block_kv", "compile_cache_dir",
                               "attention_tune_cache"])
        fluid.set_flags({"flash_block_kv": 0,
                         "compile_cache_dir": str(tmp_path / "cc"),
                         "attention_tune_cache": ""})
        try:
            # heuristic: largest candidate <= 128 dividing S
            assert at.get_decode_config(64, 8, "float32") == 64
            # tuned entry wins over the heuristic
            at.record_decode(64, 8, "float32", 16)
            assert at.get_decode_config(64, 8, "float32") == 16
            # FLAGS override wins over the tuned entry
            fluid.set_flags({"flash_block_kv": 32})
            assert at.get_decode_config(64, 8, "float32") == 32
            # a non-dividing override degrades to None (XLA fallback)
            fluid.set_flags({"flash_block_kv": 48})
            assert at.get_decode_config(64, 8, "float32") is None
        finally:
            fluid.set_flags(old)


# ---------------------------------------------------------------------------
# DecodeSession: slot table, parity, zeroing
# ---------------------------------------------------------------------------

class TestDecodeSession:
    def test_join_leave_parity_bit_exact(self, predictor):
        """The acceptance contract: greedy tokens from a running batch
        with mixed-length requests joining and LEAVING mid-flight are
        bit-identical to single-request non-batched decode."""
        sess = predictor.new_session(4)
        prompts = {0: [5, 9, 3], 1: [1, 2, 3, 4, 5, 6, 7], 2: [31, 30]}
        outs = {i: [sess.prefill(i, p)] for i, p in prompts.items()}
        for _ in range(3):
            t = sess.decode()
            for i in prompts:
                outs[i].append(int(t[i]))
        sess.free(2)                       # leaves mid-batch
        outs[3] = [sess.prefill(3, [8, 8, 8, 8])]   # joins mid-batch
        for _ in range(5):
            t = sess.decode()
            for i in (0, 1, 3):
                outs[i].append(int(t[i]))
        for i, p in [(0, prompts[0]), (1, prompts[1]),
                     (3, [8, 8, 8, 8])]:
            ref, _ = greedy_decode(predictor, p, len(outs[i]))
            assert outs[i] == ref[:len(outs[i])], \
                "slot %d diverged from single-request decode" % i
        ref2, _ = greedy_decode(predictor, prompts[2], 4)
        assert outs[2] == ref2[:4]

    def test_freed_slot_is_zeroed_and_reusable(self, predictor):
        sess = predictor.new_session(2)
        sess.prefill(0, [5, 9, 3])
        for _ in range(4):
            sess.decode()
        assert not sess.slot_is_zero(0)
        sess.free(0)
        assert sess.slot_is_zero(0), \
            "freed slot still holds the previous request's KV"
        # reuse: same prompt in the recycled slot reproduces exactly
        ref, _ = greedy_decode(predictor, [4, 4], 5)
        out = [sess.prefill(0, [4, 4])]
        for _ in range(4):
            out.append(int(sess.decode()[0]))
        assert out == ref

    def test_prompt_bucket_and_oversize_rejection(self, predictor):
        assert predictor.prompt_bucket(3) == 8
        assert predictor.prompt_bucket(8) == 8
        assert predictor.prompt_bucket(9) == 16
        # past the cache entirely still rejects; past every configured
        # bucket but inside the cache falls through with a warn-once
        # (tests/test_spec_decode.py pins the fall-through)
        with pytest.raises(ValueError, match="max_seq_len"):
            predictor.prompt_bucket(65)

    def test_eos_and_length_finish(self, predictor):
        toks, reason = greedy_decode(predictor, [5, 9, 3], 4)
        assert len(toks) == 4 and reason == "length"
        # eos finish: pick the token the model actually repeats as eos
        eos_tok = toks[-1]
        import tempfile
        d = tempfile.mkdtemp()
        build_tiny_decode_model(d, vocab_size=32, d_model=16,
                                n_heads=2, n_layers=2, max_seq_len=64,
                                eos_id=int(eos_tok), seed=7)
        p2 = GenerativePredictor(d)
        toks2, reason2 = greedy_decode(p2, [5, 9, 3], 50)
        assert reason2 == "eos"
        assert toks2[-1] == eos_tok and len(toks2) < 50


# ---------------------------------------------------------------------------
# DecodeBatcher: continuous batching semantics (in-process)
# ---------------------------------------------------------------------------

class TestDecodeBatcher:
    def test_slot_recycling_more_requests_than_slots(self, predictor):
        metrics = ServingMetrics().model("lm")
        b = DecodeBatcher(predictor, n_slots=2, metrics=metrics)
        rng = np.random.RandomState(0)
        reqs = [[int(x) for x in rng.randint(1, 32, size=n)]
                for n in (2, 5, 3, 7, 1, 4)]
        budgets = [6, 3, 9, 2, 5, 7]
        try:
            streams = [b.submit(p, max_new_tokens=m)
                       for p, m in zip(reqs, budgets)]
            outs = [s.result(timeout=60)[0].tolist() for s in streams]
        finally:
            b.close()
        for p, m, out in zip(reqs, budgets, outs):
            ref, _ = greedy_decode(predictor, p, m)
            assert out == ref, "recycled-slot stream diverged"
        assert metrics.streams.value == len(reqs)
        assert metrics.decode_tokens.value == sum(
            len(o) for o in outs)
        occupied, total = b.slot_occupancy()
        assert (occupied, total) == (0, 2)

    def test_deadline_evicts_mid_decode(self, predictor):
        """The PR 8 deadline fix: a stream past its deadline while
        GENERATING is evicted from its slot (typed error), and the slot
        serves the next request."""
        from paddle_tpu.obs import events as obs_events
        b = DecodeBatcher(predictor, n_slots=1)
        set_dispatch_delay(0.03)
        try:
            s = b.submit([5, 9, 3], max_new_tokens=200,
                         deadline=time.monotonic() + 0.2,
                         trace_id="dl-test")
            with pytest.raises(DeadlineExceeded):
                s.result(timeout=30)
            assert len(s.tokens) >= 1, \
                "expired before generating — not an in-decode eviction"
            ev = [e for e in obs_events.recent_events(
                kind="deadline_expired")
                if e.get("trace_id") == "dl-test"]
            assert ev and ev[-1].get("tokens", 0) >= 1
            set_dispatch_delay(0.0)
            # the slot is free and clean for the next stream
            ref, _ = greedy_decode(predictor, [4, 4], 5)
            nxt = b.submit([4, 4], max_new_tokens=5)
            assert nxt.result(timeout=60)[0].tolist() == ref
        finally:
            set_dispatch_delay(0.0)
            b.close()

    def test_cancel_frees_slot(self, predictor):
        b = DecodeBatcher(predictor, n_slots=1)
        set_dispatch_delay(0.02)
        try:
            s = b.submit([5, 9, 3], max_new_tokens=500)
            for _ in s.events(timeout=30):
                break  # first chunk arrived: mid-stream
            s.cancel()
            t0 = time.monotonic()
            while b.slot_occupancy()[0] and time.monotonic() - t0 < 10:
                time.sleep(0.005)
            assert b.slot_occupancy()[0] == 0, \
                "cancelled stream still pinned its slot"
        finally:
            set_dispatch_delay(0.0)
            b.close()

    def test_overload_sheds_lowest_priority_first(self, predictor):
        b = DecodeBatcher(predictor, n_slots=1, max_queue=2)
        set_dispatch_delay(0.05)
        try:
            keep = b.submit([1], max_new_tokens=50)       # occupies slot
            t0 = time.monotonic()
            while not b.slot_occupancy()[0] and \
                    time.monotonic() - t0 < 10:
                time.sleep(0.002)
            low = b.submit([2], max_new_tokens=2, priority=0)
            b.submit([3], max_new_tokens=2, priority=0)
            # queue full: a higher-priority arrival evicts `low`
            b.submit([4], max_new_tokens=2, priority=5)
            with pytest.raises(ServerOverloaded):
                low.result(timeout=5)
            # and an equal-priority arrival sheds itself
            with pytest.raises(ServerOverloaded):
                b.submit([5], max_new_tokens=2, priority=0)
            keep.cancel()
        finally:
            set_dispatch_delay(0.0)
            b.close()

    def test_static_mode_waits_for_whole_batch(self, predictor):
        """The bench baseline: a static lane admits only when idle, so
        a short request entering behind a long batch waits for ALL of
        it — the idle-slot cost continuous batching removes."""
        b = DecodeBatcher(predictor, n_slots=2, continuous=False)
        set_dispatch_delay(0.005)
        try:
            long1 = b.submit([1], max_new_tokens=40)
            long2 = b.submit([2], max_new_tokens=40)
            time.sleep(0.05)  # batch is running
            short = b.submit([3], max_new_tokens=1)
            short.result(timeout=60)
            assert long1.done() and long2.done(), \
                "static mode admitted into a running batch"
        finally:
            set_dispatch_delay(0.0)
            b.close()


# ---------------------------------------------------------------------------
# wire streaming end-to-end
# ---------------------------------------------------------------------------

class TestServerStream:
    def test_three_concurrent_clients_ordered_complete_streams(
            self, artifact, predictor):
        """Acceptance: 3 concurrent streaming clients with different
        lengths; every client's concatenated chunks equal its
        single-request reference IN ORDER, with a final frame naming
        the finish reason."""
        server = InferenceServer().start()
        boot = ServingClient(server.endpoint)
        prompts = [[5, 9, 3], [1, 2, 3, 4, 5, 6, 7], [31, 30]]
        budgets = [9, 4, 12]
        outs = [None] * 3
        infos = [None] * 3
        errs = []
        try:
            boot.load_model("lm", artifact, decode_slots=2)

            def worker(i):
                cli = ServingClient(server.endpoint)
                try:
                    chunks = list(cli.infer_stream(
                        "lm", prompts[i], max_new_tokens=budgets[i],
                        deadline_ms=60000.0))
                    outs[i] = [t for c in chunks for t in c]
                    infos[i] = cli.last_stream_info
                except Exception as e:
                    errs.append(e)
                finally:
                    cli.close()

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errs, errs[:3]
            for i in range(3):
                ref, reason = greedy_decode(predictor, prompts[i],
                                            budgets[i])
                assert outs[i] == ref, \
                    "client %d stream diverged: %s vs %s" \
                    % (i, outs[i], ref)
                assert infos[i]["finish_reason"] == reason
                assert infos[i]["new_tokens"] == len(ref)
                assert infos[i].get("trace_id")
        finally:
            boot.close()
            server.shutdown(drain=True)

    def test_chunk_grouping_and_oneshot_verb(self, artifact, predictor):
        server = InferenceServer().start()
        cli = ServingClient(server.endpoint)
        try:
            cli.load_model("lm", artifact, decode_slots=2)
            ref, reason = greedy_decode(predictor, [5, 9, 3], 9)
            # grouped flush: every chunk <= 4 tokens, nothing lost
            chunks = list(cli.infer_stream("lm", [5, 9, 3],
                                           max_new_tokens=9,
                                           deadline_ms=60000.0,
                                           chunk_tokens=4))
            assert all(len(c) <= 4 for c in chunks)
            assert [t for c in chunks for t in c] == ref
            # one-shot verb on a decode model: whole greedy stream
            out = cli.infer("lm", {"tokens": np.array([5, 9, 3])},
                            max_new_tokens=9, deadline_ms=60000.0)
            assert out[0].tolist() == ref
            # stats carry the decode telemetry
            snap = cli.stats()["stats"]["models"]["lm"]
            assert snap["streams"] == 2
            assert snap["decode_tokens"] == 2 * len(ref)
            assert snap["ttft_ms"]["count"] == 2
            assert "slot_occupancy" in snap
            desc = cli.stats()["models"]["lm"]
            assert desc.get("decode") is True
            assert desc.get("decode_slots") == 2
        finally:
            cli.close()
            server.shutdown(drain=True)

    def test_stream_deadline_error_frame(self, artifact):
        server = InferenceServer().start()
        cli = ServingClient(server.endpoint)
        set_dispatch_delay(0.03)
        try:
            cli.load_model("lm", artifact, decode_slots=1)
            got = []
            with pytest.raises(DeadlineExceeded):
                for chunk in cli.infer_stream("lm", [5, 9, 3],
                                              max_new_tokens=300,
                                              deadline_ms=250.0):
                    got.extend(chunk)
            assert got, "typed error frame should follow streamed tokens"
        finally:
            set_dispatch_delay(0.0)
            cli.close()
            server.shutdown(drain=False, timeout=10.0)

    def test_client_disconnect_frees_slot(self, artifact):
        server = InferenceServer().start()
        boot = ServingClient(server.endpoint)
        set_dispatch_delay(0.02)
        try:
            boot.load_model("lm", artifact, decode_slots=1)
            victim = ServingClient(server.endpoint)
            it = victim.infer_stream("lm", [5, 9, 3],
                                     max_new_tokens=500)
            next(it)           # stream is live
            it.close()         # connection drops mid-stream
            victim.close()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 10:
                snap = boot.stats()["stats"]["models"]["lm"]
                if snap.get("decode_slots_busy", 1) == 0:
                    break
                time.sleep(0.01)
            else:
                pytest.fail("slot still occupied after disconnect")
            set_dispatch_delay(0.0)
            # lane is not wedged: the freed slot serves new traffic
            out = boot.infer("lm", {"tokens": np.array([4, 4])},
                             max_new_tokens=3, deadline_ms=60000.0)
            assert len(out[0]) == 3
        finally:
            set_dispatch_delay(0.0)
            boot.close()
            server.shutdown(drain=False, timeout=10.0)

    def test_metrics_rpc_exports_decode_families(self, artifact):
        server = InferenceServer().start()
        cli = ServingClient(server.endpoint)
        try:
            cli.load_model("lm", artifact, decode_slots=2)
            list(cli.infer_stream("lm", [5, 9, 3], max_new_tokens=4,
                                  deadline_ms=60000.0))
            text = cli.metrics_text()
            for family in ("serving_decode_tokens_total",
                           "serving_tokens_per_sec",
                           "serving_slot_occupancy",
                           "serving_ttft_ms"):
                assert family in text, "missing %s in:\n%s" \
                    % (family, text[:2000])
        finally:
            cli.close()
            server.shutdown(drain=True)


# ---------------------------------------------------------------------------
# compile-cache warm hit for the decode phases
# ---------------------------------------------------------------------------

class TestDecodeCompileCache:
    def test_prefill_buckets_warm_hit_zero_fresh_compiles(
            self, artifact, tmp_path):
        from paddle_tpu import compile_cache as cc
        from paddle_tpu.serving import ModelRegistry
        old = fluid.get_flags(["compile_cache", "compile_cache_dir"])
        fluid.set_flags({"compile_cache": True,
                         "compile_cache_dir": str(tmp_path / "cc")})
        cc.reset_stats()
        try:
            reg = ModelRegistry()
            reg.load_model("lm", artifact, decode_slots=2)
            cold = cc.stats()
            assert cold["misses"] >= 2, \
                "cold load should compile+commit prefill buckets + step"
            reg.close_all()
            # second load of the same artifact: every decode-phase
            # executable deserializes from the store — zero fresh
            # compiles, same tokens
            before = cc.stats()
            reg2 = ModelRegistry()
            reg2.load_model("lm", artifact, decode_slots=2)
            delta = cc.stats_delta(before)
            assert delta["misses"] == 0, delta
            assert delta["hits"] >= cold["misses"], delta
            out = reg2.submit("lm", {"tokens": [5, 9, 3]},
                              max_new_tokens=4).result(timeout=60)
            pred = GenerativePredictor(artifact)
            ref, _ = greedy_decode(pred, [5, 9, 3], 4)
            assert out[0].tolist() == ref
            reg2.close_all()
        finally:
            fluid.set_flags(old)
            cc.reset_stats()

    def test_fingerprint_covers_weights_not_just_meta(self, tmp_path):
        """Two artifacts with IDENTICAL meta (same dims/vocab/eos) but
        different weights must never resolve each other's persisted
        executables: the int8 phases bake weight-derived kv scales as
        trace constants, so a meta-only fingerprint let a stale
        ("step", n) blob quantize one model's rows with another
        model's scales (the cross-artifact cache-poisoning bug the
        decode-disconnect-int8 chaos scenario caught)."""
        a = str(tmp_path / "seed21")
        b = str(tmp_path / "seed22")
        kw = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                  max_seq_len=64, eos_id=-1)
        build_tiny_decode_model(a, seed=21, **kw)
        build_tiny_decode_model(b, seed=22, **kw)
        pa = GenerativePredictor(a, kv_cache_dtype="int8")
        pb = GenerativePredictor(b, kv_cache_dtype="int8")
        assert pa.meta == pb.meta
        assert pa._model_fp != pb._model_fp
        # and the full phase fingerprints diverge too — the store can
        # never hand one model the other's baked-scale executable
        import jax
        spec = (jax.ShapeDtypeStruct((2, 2, 64, 4, 8),
                                     __import__("numpy").int8),)
        fpa = pa._fingerprint(("step", 2), spec)
        fpb = pb._fingerprint(("step", 2), spec)
        assert fpa != fpb
        # same artifact reopened: fingerprint is stable (warm reloads
        # keep deserializing)
        assert GenerativePredictor(
            a, kv_cache_dtype="int8")._model_fp == pa._model_fp


# ---------------------------------------------------------------------------
# fused multi-step decode (SERVING.md "Fused multi-step decode")
# ---------------------------------------------------------------------------

class TestFusedDecode:
    def test_fused_vs_single_step_churn_parity(self, predictor):
        """The fused acceptance contract: a batcher dispatching N=8
        steps per device call, with more requests than slots (joins and
        leaves land at window boundaries), streams BIT-IDENTICAL tokens
        to the single-step greedy oracle — and cuts dispatches ~N-fold
        (decode_dispatches + tokens_per_dispatch tell the story)."""
        metrics = ServingMetrics().model("lm")
        b = DecodeBatcher(predictor, n_slots=2, metrics=metrics,
                          fuse_steps=8)
        rng = np.random.RandomState(1)
        reqs = [[int(x) for x in rng.randint(1, 32, size=n)]
                for n in (2, 5, 3, 7, 1, 4)]
        budgets = [6, 3, 9, 2, 12, 7]
        try:
            streams = [b.submit(p, max_new_tokens=m)
                       for p, m in zip(reqs, budgets)]
            outs = [s.result(timeout=60)[0].tolist() for s in streams]
        finally:
            b.close()
        for p, m, out in zip(reqs, budgets, outs):
            ref, _ = greedy_decode(predictor, p, m)
            assert out == ref, "fused stream diverged from N=1 oracle"
        total = sum(len(o) for o in outs)
        assert metrics.decode_tokens.value == total
        dispatches = metrics.decode_dispatches.value
        assert dispatches >= 1
        # windows amortize: far fewer dispatches than tokens, and the
        # histogram saw every dispatch
        assert dispatches < total, (dispatches, total)
        assert metrics.tokens_per_dispatch.count == dispatches

    def test_fused_eos_early_exit_mid_window(self, predictor):
        """A slot hitting EOS mid-window stops the while_loop early:
        the dispatch returns fewer trips than the window, the EOS token
        itself is emitted, and the stream equals the greedy oracle."""
        # pick an eos id whose FIRST occurrence in the greedy stream is
        # mid-window (index >= 4) so the early exit is provoked for
        # real, not at the prefill token
        probe, _ = greedy_decode(predictor, [5, 9, 3], 14)
        j = next(i for i in range(4, len(probe))
                 if probe[i] not in probe[:i])
        eos_tok = int(probe[j])
        import tempfile
        d = tempfile.mkdtemp()
        build_tiny_decode_model(d, vocab_size=32, d_model=16,
                                n_heads=2, n_layers=2, max_seq_len=64,
                                eos_id=eos_tok, seed=7)
        p2 = GenerativePredictor(d)
        ref, reason = greedy_decode(p2, [5, 9, 3], 50)
        assert reason == "eos" and len(ref) == j + 1
        sess = p2.new_session(2)
        first = sess.prefill(0, [5, 9, 3])
        n_window = j + 6   # EOS lands with trips to spare
        toks, counts, trips = sess.decode_fused(n_window)
        assert trips < n_window, \
            "EOS mid-window did not early-exit the fused loop"
        out = [first] + [int(toks[0, i]) for i in range(int(counts[0]))]
        assert out == ref, "fused EOS stream diverged: %s vs %s" \
            % (out, ref)
        assert out[-1] == eos_tok

    def test_fused_warm_reload_all_hits(self, artifact, tmp_path):
        """The fused executables ride the persistent compile cache
        under their own fingerprints: a second fuse_steps>1 load is
        all hits, zero fresh compiles, same tokens."""
        from paddle_tpu import compile_cache as cc
        from paddle_tpu.serving import ModelRegistry
        old = fluid.get_flags(["compile_cache", "compile_cache_dir"])
        fluid.set_flags({"compile_cache": True,
                         "compile_cache_dir": str(tmp_path / "cc")})
        cc.reset_stats()
        try:
            reg = ModelRegistry()
            reg.load_model("lm", artifact, decode_slots=2,
                           fuse_steps=4)
            cold = cc.stats()
            assert cold["misses"] >= 2
            reg.close_all()
            before = cc.stats()
            reg2 = ModelRegistry()
            reg2.load_model("lm", artifact, decode_slots=2,
                            fuse_steps=4)
            delta = cc.stats_delta(before)
            assert delta["misses"] == 0, delta
            assert delta["hits"] >= cold["misses"], delta
            out = reg2.submit("lm", {"tokens": [5, 9, 3]},
                              max_new_tokens=6).result(timeout=60)
            pred = GenerativePredictor(artifact)
            ref, _ = greedy_decode(pred, [5, 9, 3], 6)
            assert out[0].tolist() == ref
            reg2.close_all()
        finally:
            fluid.set_flags(old)
            cc.reset_stats()

    def test_fused_deadline_overshoot_bounded(self, predictor):
        """The satellite bugfix: deadline checks only fire between
        dispatches, so the EWMA trip clamp must bound the overshoot to
        about ONE fused dispatch — and the deadline_expired event
        stamps `overshoot_ms`."""
        from paddle_tpu.obs import events as obs_events
        b = DecodeBatcher(predictor, n_slots=1, fuse_steps=4)
        try:
            # warm the fused executable first: the clamp guarantee is
            # about steady-state step cost, not the one-off compile
            b.submit([4, 4], max_new_tokens=8).result(timeout=60)
            set_dispatch_delay(0.03)
            s = b.submit([5, 9, 3], max_new_tokens=200,
                         deadline=time.monotonic() + 0.25,
                         trace_id="fdl-test")
            with pytest.raises(DeadlineExceeded):
                s.result(timeout=30)
            assert len(s.tokens) >= 1
            ev = [e for e in obs_events.recent_events(
                kind="deadline_expired")
                if e.get("trace_id") == "fdl-test"]
            assert ev, "no deadline_expired event"
            over = ev[-1].get("overshoot_ms")
            assert over is not None, "event missing overshoot_ms"
            # one fused dispatch is 4 x 30ms; generous host slack on
            # top still proves the clamp beat the unclamped window tail
            assert over <= 4 * 30.0 + 500.0, over
        finally:
            set_dispatch_delay(0.0)
            b.close()


def test_fused_gate_smoke(artifact, predictor):
    """The ci_checks.sh `fused_decode` gate body (exit 17): a served
    fuse_steps=4 stream is BIT-EXACT vs the N=1 greedy oracle and the
    dispatch count amortizes (~N tokens per dispatch)."""
    server = InferenceServer().start()
    cli = ServingClient(server.endpoint)
    try:
        loaded = cli.load_model("lm", artifact, decode_slots=2,
                                fuse_steps=4)
        assert loaded.get("fuse_steps") == 4
        for prompt, budget in [([5, 9, 3], 12), ([1, 2, 3, 4], 9)]:
            ref, _ = greedy_decode(predictor, prompt, budget)
            out = [t for c in cli.infer_stream(
                "lm", prompt, max_new_tokens=budget,
                deadline_ms=60000.0) for t in c]
            assert out == ref, "fused served stream diverged"
        snap = cli.stats()["stats"]["models"]["lm"]
        assert snap["decode_dispatches"] >= 1
        tpd = snap["decode_tokens"] / float(snap["decode_dispatches"])
        assert tpd >= 2.0, \
            "tokens/dispatch %.2f — fusion not amortizing" % tpd
        desc = cli.stats()["models"]["lm"]
        assert desc.get("fuse_steps") == 4
    finally:
        cli.close()
        server.shutdown(drain=True)


# ---------------------------------------------------------------------------
# tools
# ---------------------------------------------------------------------------

def test_serving_top_renders_decode_columns(artifact, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serving_top
    server = InferenceServer().start()
    cli = ServingClient(server.endpoint)
    try:
        cli.load_model("lm", artifact, decode_slots=2)
        list(cli.infer_stream("lm", [5, 9, 3], max_new_tokens=4,
                              deadline_ms=60000.0))
        serving_top.main([server.endpoint])
        out = capsys.readouterr().out
        assert "TTFT95" in out and "TPS" in out and "OCC%" in out
        assert "TPD" in out
        assert "decode_slots=2" in out
    finally:
        cli.close()
        server.shutdown(drain=True)


def test_bench_serving_decode_smoke_subprocess():
    """Tier-1-adjacent proof of the whole decode lane in a fresh
    process: build artifact, serve, stream under open-loop load, JSON
    record with bit_exact=True."""
    import json
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_serving.py"),
         "--decode", "--smoke", "--duration", "3", "--qps", "6"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, proc.stdout[-500:]
    rec = json.loads(lines[-1])
    assert rec["metric"] == "serving_decode"
    assert rec["mode"] == "cb"
    assert rec["ok"] > 0 and rec["errors"] == 0
    assert rec["bit_exact"] is True
    assert rec["tokens_per_sec"] > 0
    assert rec["ttft_p95_ms"] is not None


def test_chaos_decode_disconnect_scenario():
    """The chaos scenario doubles as the slot-reclaim + no-leakage
    acceptance test; run it in-process (it asserts internally)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos
    res = chaos.scenario_decode_disconnect(verbose=False)
    assert res["freed_steps"] <= 6
    assert res["expired_tokens"] >= 1


def test_chaos_decode_disconnect_fused_scenario():
    """The fused-boundary chaos scenario: mid-window disconnects free
    at the next dispatch boundary, deadline overshoot is clamped to
    ~one fused dispatch with overshoot_ms stamped, reused slots stream
    bit-exact (it asserts internally)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos
    res = chaos.scenario_decode_disconnect_fused(verbose=False)
    assert res["freed_steps"] <= 3 * res["fuse_steps"]
    assert res["overshoot_ms"] is not None
