// Bounded blocking byte-buffer queue.
//
// Reference analogue: operators/reader/lod_tensor_blocking_queue.h:31
// (LoDTensorBlockingQueue) + blocking_queue.h — the Python->C++ handoff of
// the py_reader pipeline. The queue holds serialized batches (bytes);
// producers (Python feeder threads, which release the GIL inside ctypes
// calls) block when full, the consumer blocks when empty — true parallelism
// the pure-Python queue.Queue can't give while numpy serialization runs.

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>

namespace {

struct Queue {
  std::deque<std::string> items;
  size_t capacity;
  bool closed = false;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
};

}  // namespace

extern "C" {

void* bq_create(long capacity) {
  auto* q = new Queue();
  q->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 1;
  return q;
}

// 0 = pushed, -1 = closed, -2 = timeout
int bq_push(void* handle, const uint8_t* buf, long len, long timeout_ms) {
  auto* q = static_cast<Queue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [q] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_ms < 0) {
    q->not_full.wait(lk, pred);
  } else if (!q->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
    return -2;
  }
  if (q->closed) return -1;
  q->items.emplace_back(reinterpret_cast<const char*>(buf),
                        static_cast<size_t>(len));
  q->not_empty.notify_one();
  return 0;
}

// Returns length >= 0 with *out = malloc'd buffer (free with bq_free);
// -1 = closed and drained, -2 = timeout.
long bq_pop(void* handle, uint8_t** out, long timeout_ms) {
  auto* q = static_cast<Queue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [q] { return q->closed || !q->items.empty(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(lk, pred);
  } else if (!q->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
    return -2;
  }
  if (q->items.empty()) return -1;  // closed + drained
  std::string item = std::move(q->items.front());
  q->items.pop_front();
  q->not_full.notify_one();
  lk.unlock();
  auto* buf = static_cast<uint8_t*>(malloc(item.size() ? item.size() : 1));
  memcpy(buf, item.data(), item.size());
  *out = buf;
  return static_cast<long>(item.size());
}

void bq_free(uint8_t* buf) { free(buf); }

long bq_size(void* handle) {
  auto* q = static_cast<Queue*>(handle);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<long>(q->items.size());
}

void bq_close(void* handle) {
  auto* q = static_cast<Queue*>(handle);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

void bq_destroy(void* handle) {
  bq_close(handle);
  delete static_cast<Queue*>(handle);
}

}  // extern "C"
