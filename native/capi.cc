// C inference API implementation — see pd_capi.h for the contract and
// the reference mapping (paddle_api.h:134 PaddlePredictor::Run; legacy
// capi paddle_matrix/paddle_gradient_machine surface).
//
// Design: the serving computation is an AOT-exported XLA module
// (paddle_tpu/inference/predictor.py AotPredictor — no Program rebuild,
// no trace). CPython is embedded purely as host glue: ~200 lines of
// dict/ndarray plumbing per call, nanoseconds next to an XLA dispatch.
// numpy interop deliberately uses the buffer protocol + frombuffer
// instead of the numpy C API so the .so builds against libpython alone.

#include "pd_capi.h"

#include <Python.h>

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

namespace {

// thread_local: pd_last_error() is read without the GIL, and one
// thread's failure must not clobber another's message
thread_local std::string g_err;

struct DtypeEntry {
  int code;
  const char *np_name;
  size_t size;
};

const DtypeEntry kDtypes[] = {
    {PD_FLOAT32, "float32", 4}, {PD_FLOAT64, "float64", 8},
    {PD_INT32, "int32", 4},     {PD_INT64, "int64", 8},
    {PD_UINT8, "uint8", 1},     {PD_BOOL, "bool", 1},
};

const DtypeEntry *dtype_by_code(int code) {
  for (const auto &e : kDtypes)
    if (e.code == code) return &e;
  return nullptr;
}

const DtypeEntry *dtype_by_np_name(const char *name) {
  for (const auto &e : kDtypes)
    if (std::strcmp(e.np_name, name) == 0) return &e;
  return nullptr;
}

void set_err_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_err = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) g_err = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Initialize the interpreter once and release the GIL so every API call
// can use PyGILState_Ensure/Release symmetrically. std::call_once makes
// concurrent first calls from several threads safe: losers block until
// the interpreter is up (or init failed) instead of racing the flags.
bool ensure_python() {
  static std::once_flag flag;
  static bool ok = false;
  static std::string init_err;
  std::call_once(flag, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      const char *plat = std::getenv("PD_CAPI_PLATFORM");
      if (plat && *plat) {
        // pin the platform BEFORE any jax backend init (a sitecustomize
        // may already have imported jax; config.update still wins as
        // long as no backend came up)
        std::string code = "import jax\n"
                           "jax.config.update('jax_platforms', '";
        code += plat;
        code += "')\n";
        if (PyRun_SimpleString(code.c_str()) != 0) {
          init_err = std::string("PD_CAPI_PLATFORM pin failed for "
                                 "platform: ") + plat;
          PyEval_SaveThread();
          return;
        }
      }
      PyEval_SaveThread();  // drop the GIL held since Py_InitializeEx
    }
    ok = true;
  });
  if (!ok) g_err = init_err.empty() ? "python init failed" : init_err;
  return ok;
}

struct Predictor {
  PyObject *pred;         // AotPredictor / AotTrainer instance
  PyObject *np;           // numpy module
  PyObject *feed_names;   // list[str]
  PyObject *fetch_names;  // list[str]
};

// np.frombuffer(bytes, dtype=...).reshape(dims) for one input tensor.
PyObject *tensor_to_ndarray(const Predictor *p, const pd_tensor *t) {
  const DtypeEntry *de = dtype_by_code(t->dtype);
  if (!de) {
    g_err = "unknown input dtype code";
    return nullptr;
  }
  // validate BEFORE iterating dims: a garbage ndim would walk past the
  // fixed dims[PD_MAX_DIMS] array (the output path already checks)
  if (t->ndim < 0 || t->ndim > PD_MAX_DIMS) {
    g_err = "input rank outside [0, PD_MAX_DIMS]";
    return nullptr;
  }
  size_t count = 1;
  for (int i = 0; i < t->ndim; ++i) {
    if (t->dims[i] < 0) {
      g_err = "negative input dim";
      return nullptr;
    }
    count *= (size_t)t->dims[i];
  }
  if (t->nbytes != count * de->size) {
    g_err = "input nbytes does not match dims*itemsize";
    return nullptr;
  }
  // zero-copy view of the caller's buffer: safe because run() is
  // synchronous (the predictor copies on astype/jnp.asarray before the
  // call returns) and the caller owns the input for the call's duration
  PyObject *mv = PyMemoryView_FromMemory((char *)t->data,
                                         (Py_ssize_t)t->nbytes, PyBUF_READ);
  if (!mv) return nullptr;
  PyObject *flat = PyObject_CallMethod(p->np, "frombuffer", "Os", mv,
                                       de->np_name);
  Py_DECREF(mv);
  if (!flat) return nullptr;
  PyObject *shape = PyTuple_New(t->ndim);
  if (!shape) {
    Py_DECREF(flat);
    return nullptr;
  }
  for (int i = 0; i < t->ndim; ++i)
    PyTuple_SET_ITEM(shape, i, PyLong_FromLongLong(t->dims[i]));
  PyObject *arr = PyObject_CallMethod(flat, "reshape", "O", shape);
  Py_DECREF(flat);
  Py_DECREF(shape);
  return arr;
}

// Copy one ndarray out into a malloc'd pd_tensor.
bool ndarray_to_tensor(const Predictor *p, PyObject *arr_in,
                       PyObject *name_obj, pd_tensor *out) {
  std::memset(out, 0, sizeof(*out));
  PyObject *arr =
      PyObject_CallMethod(p->np, "ascontiguousarray", "O", arr_in);
  if (!arr) return false;
  bool ok = false;
  PyObject *dt = nullptr, *dt_name = nullptr, *shape = nullptr;
  Py_buffer view;
  std::memset(&view, 0, sizeof(view));
  do {
    dt = PyObject_GetAttrString(arr, "dtype");
    if (!dt) break;
    dt_name = PyObject_GetAttrString(dt, "name");
    if (!dt_name) break;
    const char *np_name = PyUnicode_AsUTF8(dt_name);
    const DtypeEntry *de = np_name ? dtype_by_np_name(np_name) : nullptr;
    if (!de) {
      g_err = std::string("unsupported output dtype: ") +
              (np_name ? np_name : "?");
      break;
    }
    shape = PyObject_GetAttrString(arr, "shape");
    if (!shape) break;
    Py_ssize_t ndim = PyTuple_Size(shape);
    if (ndim > PD_MAX_DIMS) {
      g_err = "output rank exceeds PD_MAX_DIMS";
      break;
    }
    out->dtype = de->code;
    out->ndim = (int)ndim;
    for (Py_ssize_t i = 0; i < ndim; ++i)
      out->dims[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(shape, i));
    if (PyObject_GetBuffer(arr, &view, PyBUF_CONTIG_RO) != 0) break;
    out->nbytes = (size_t)view.len;
    out->data = std::malloc(out->nbytes ? out->nbytes : 1);
    if (!out->data) {
      g_err = "malloc failed";
      break;
    }
    std::memcpy(out->data, view.buf, out->nbytes);
    if (name_obj) {
      const char *nm = PyUnicode_AsUTF8(name_obj);
      if (nm) {
        std::strncpy(out->name, nm, PD_MAX_NAME - 1);
        out->name[PD_MAX_NAME - 1] = '\0';
      }
    }
    ok = true;
  } while (false);
  if (view.obj) PyBuffer_Release(&view);
  Py_XDECREF(shape);
  Py_XDECREF(dt_name);
  Py_XDECREF(dt);
  Py_DECREF(arr);
  return ok;
}

// Shared constructor: import `factory` from `mod_name`, call it on
// model_dir, keep the instance + its feed/fetch name lists.
Predictor *create_host(const char *mod_name, const char *factory,
                       const char *model_dir) {
  g_err.clear();
  if (!ensure_python()) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  Predictor *p = nullptr;
  PyObject *mod = nullptr, *np = nullptr, *pred = nullptr;
  do {
    np = PyImport_ImportModule("numpy");
    if (!np) {
      set_err_from_python();
      break;
    }
    mod = PyImport_ImportModule(mod_name);
    if (!mod) {
      set_err_from_python();
      break;
    }
    pred = PyObject_CallMethod(mod, factory, "s", model_dir);
    if (!pred) {
      set_err_from_python();
      break;
    }
    PyObject *feeds = PyObject_GetAttrString(pred, "_feed_names");
    PyObject *fetches = PyObject_GetAttrString(pred, "_fetch_names");
    if (!feeds || !fetches) {
      Py_XDECREF(feeds);
      Py_XDECREF(fetches);
      set_err_from_python();
      break;
    }
    p = new Predictor{pred, np, feeds, fetches};
    pred = nullptr;  // ownership moved
    np = nullptr;
  } while (false);
  Py_XDECREF(pred);
  Py_XDECREF(np);
  Py_XDECREF(mod);
  if (PyErr_Occurred()) PyErr_Clear();  // never leak a pending exception
  PyGILState_Release(gil);
  return p;
}

void destroy_host(Predictor *p) {
  if (!p) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->pred);
  Py_XDECREF(p->np);
  Py_XDECREF(p->feed_names);
  Py_XDECREF(p->fetch_names);
  PyGILState_Release(gil);
  delete p;
}

}  // namespace

extern "C" {

void *pd_create_predictor(const char *model_dir) {
  return create_host("paddle_tpu.inference", "load_aot_predictor",
                     model_dir);
}

// The predictor's run() and the trainer's step() share the exact feed /
// fetch marshalling; only the bound method differs.
static int run_host_method(void *predictor, const char *method,
                           const pd_tensor *inputs, int n_in,
                           pd_tensor *outputs, int max_out) {
  g_err.clear();
  Predictor *p = (Predictor *)predictor;
  if (!p) {
    g_err = "null handle";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int result = -1;
  PyObject *feeds = nullptr, *outs = nullptr;
  do {
    feeds = PyDict_New();
    if (!feeds) break;
    bool bad = false;
    for (int i = 0; i < n_in; ++i) {
      PyObject *arr = tensor_to_ndarray(p, &inputs[i]);
      if (!arr) {
        if (g_err.empty()) set_err_from_python();
        bad = true;
        break;
      }
      int rc;
      if (inputs[i].name[0]) {
        rc = PyDict_SetItemString(feeds, inputs[i].name, arr);
      } else {
        PyObject *nm = PyList_GetItem(p->feed_names, i);  // borrowed
        if (!nm) {
          g_err = "more inputs than model feeds";
          Py_DECREF(arr);
          bad = true;
          break;
        }
        rc = PyDict_SetItem(feeds, nm, arr);
      }
      Py_DECREF(arr);
      if (rc != 0) {
        set_err_from_python();
        bad = true;
        break;
      }
    }
    if (bad) break;
    outs = PyObject_CallMethod(p->pred, method, "O", feeds);
    if (!outs) {
      set_err_from_python();
      break;
    }
    Py_ssize_t n_out = PySequence_Size(outs);
    if (n_out < 0) {
      set_err_from_python();
      break;
    }
    bool copy_ok = true;
    for (Py_ssize_t i = 0; i < n_out && i < max_out; ++i) {
      PyObject *item = PySequence_GetItem(outs, i);
      if (!item) {
        set_err_from_python();
        copy_ok = false;
        break;
      }
      PyObject *nm = (i < PyList_Size(p->fetch_names))
                         ? PyList_GetItem(p->fetch_names, i)
                         : nullptr;  // borrowed
      bool one = ndarray_to_tensor(p, item, nm, &outputs[i]);
      Py_DECREF(item);
      if (!one) {
        if (g_err.empty()) set_err_from_python();
        // release anything already copied so the caller need not
        for (Py_ssize_t j = 0; j < i; ++j) pd_free_tensor_data(&outputs[j]);
        copy_ok = false;
        break;
      }
    }
    if (!copy_ok) break;
    result = (int)n_out;
  } while (false);
  Py_XDECREF(outs);
  Py_XDECREF(feeds);
  if (PyErr_Occurred()) PyErr_Clear();  // never leak a pending exception
  PyGILState_Release(gil);
  return result;
}

int pd_predictor_run(void *predictor, const pd_tensor *inputs, int n_in,
                     pd_tensor *outputs, int max_out) {
  return run_host_method(predictor, "run", inputs, n_in, outputs,
                         max_out);
}

void pd_free_tensor_data(pd_tensor *t) {
  if (t && t->data) {
    std::free(t->data);
    t->data = nullptr;
    t->nbytes = 0;
  }
}

void pd_destroy_predictor(void *predictor) {
  destroy_host((Predictor *)predictor);
}

/* ---- training (reference train/demo analogue) ---------------------- */

void *pd_create_trainer(const char *model_dir) {
  return create_host("paddle_tpu.fluid.train_export", "load_aot_trainer",
                     model_dir);
}

int pd_trainer_step(void *trainer, const pd_tensor *inputs, int n_in,
                    pd_tensor *outputs, int max_out) {
  return run_host_method(trainer, "step", inputs, n_in, outputs,
                         max_out);
}

int pd_trainer_save(void *trainer, const char *dirname) {
  g_err.clear();
  Predictor *p = (Predictor *)trainer;
  if (!p) {
    g_err = "null handle";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject *r = PyObject_CallMethod(p->pred, "save", "s", dirname);
  if (r) {
    rc = 0;
    Py_DECREF(r);
  } else {
    set_err_from_python();
  }
  if (PyErr_Occurred()) PyErr_Clear();
  PyGILState_Release(gil);
  return rc;
}

void pd_destroy_trainer(void *trainer) {
  destroy_host((Predictor *)trainer);
}

const char *pd_last_error(void) { return g_err.c_str(); }

}  // extern "C"
