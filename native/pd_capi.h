/* C inference API for paddle_tpu.
 *
 * Reference analogue: the C++ PaddlePredictor / CreatePaddlePredictor
 * surface (paddle/fluid/inference/api/paddle_api.h:134,:204) and the
 * legacy pure-C capi (paddle/legacy/capi). TPU redesign: the model is a
 * `Predictor.save_aot` artifact (versioned StableHLO + weights); this
 * library embeds CPython as host glue to feed the XLA computation, so a
 * C/C++ application links one .so and serves with no Python of its own.
 *
 * Threading: calls are serialized internally via the GIL. Buffers in
 * `pd_tensor.data` are caller-owned for inputs; for outputs they are
 * malloc'd by the library and released with pd_free_tensor_data().
 *
 * Env: PD_CAPI_PLATFORM=cpu|tpu pins the jax platform before backend
 * init (needed on hosts whose default platform is unavailable).
 */
#ifndef PD_CAPI_H
#define PD_CAPI_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

enum pd_dtype {
  PD_FLOAT32 = 0,
  PD_FLOAT64 = 1,
  PD_INT32 = 2,
  PD_INT64 = 3,
  PD_UINT8 = 4,
  PD_BOOL = 5,
};

#define PD_MAX_DIMS 8
#define PD_MAX_NAME 64

typedef struct pd_tensor {
  int dtype;                 /* enum pd_dtype */
  int ndim;
  int64_t dims[PD_MAX_DIMS];
  void *data;                /* contiguous, C order */
  size_t nbytes;
  char name[PD_MAX_NAME];    /* "" on input = positional feed order */
} pd_tensor;

/* Open a save_aot artifact directory. NULL on failure (pd_last_error). */
void *pd_create_predictor(const char *model_dir);

/* Run one batch. Fills up to max_out tensors (malloc'd data; free each
 * with pd_free_tensor_data). Returns the number of model outputs, or -1
 * on failure. If the model has more outputs than max_out, the first
 * max_out are filled and the true count is returned. */
int pd_predictor_run(void *predictor, const pd_tensor *inputs, int n_in,
                     pd_tensor *outputs, int max_out);

void pd_free_tensor_data(pd_tensor *t);

void pd_destroy_predictor(void *predictor);

/* ---- training from a saved artifact ---------------------------------
 * Reference analogue: the C++ train/demo (paddle/fluid/train/demo/
 * demo_trainer.cc) — training driven from a saved program with no
 * Python of the application's own. The artifact is written by
 * paddle_tpu.fluid.train_export.save_aot_trainer: the whole optimizer
 * step (forward+backward+update) as one AOT StableHLO module, with the
 * parameter/optimizer state threaded through each call. */

/* Open a save_aot_trainer artifact. NULL on failure (pd_last_error). */
void *pd_create_trainer(const char *model_dir);

/* One optimizer step: feeds in, per-step fetches (losses) out. Same
 * tensor conventions as pd_predictor_run. Parameter state advances
 * inside the handle. Returns fetch count, or -1 on failure. */
int pd_trainer_step(void *trainer, const pd_tensor *inputs, int n_in,
                    pd_tensor *outputs, int max_out);

/* Checkpoint state + step counter into dirname (may equal the source
 * artifact dir). A later pd_create_trainer on that dir resumes exactly.
 * Returns 0, or -1 on failure. */
int pd_trainer_save(void *trainer, const char *dirname);

void pd_destroy_trainer(void *trainer);

/* Last error message (empty string when the previous call succeeded). */
const char *pd_last_error(void);

#ifdef __cplusplus
}
#endif

#endif /* PD_CAPI_H */
