// RecordIO: chunked, checksummed record file format.
//
// Reference analogue: paddle/fluid/recordio/ (chunk.h:27 Chunk,
// scanner.h:26 Scanner; 711 LoC C++) — the dataset container the reference's
// open_files/recordio reader ops consume. Re-designed, not ported: same
// capability (appendable chunks, per-chunk CRC32, streaming scan), fresh
// layout.
//
// File layout:
//   [8-byte magic "PTRIO001"]
//   chunk*:
//     u32 num_records | u32 payload_len | u32 crc32(payload) | u32 reserved
//     u32 len[num_records]
//     payload (concatenated records)
//
// Exposed as a C API for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr char kMagic[8] = {'P', 'T', 'R', 'I', 'O', '0', '0', '1'};

// CRC-32 (IEEE), table-driven.
uint32_t crc_table[256];
bool crc_init_done = false;

void init_crc_table() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32_buf(const uint8_t* buf, size_t len) {
  init_crc_table();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Writer {
  FILE* f = nullptr;
  std::vector<std::string> pending;
  size_t pending_bytes = 0;
  size_t max_chunk_records;
  size_t max_chunk_bytes;

  bool flush_chunk() {
    if (pending.empty()) return true;
    std::string payload;
    payload.reserve(pending_bytes);
    std::vector<uint32_t> lens;
    lens.reserve(pending.size());
    for (auto& r : pending) {
      lens.push_back(static_cast<uint32_t>(r.size()));
      payload += r;
    }
    uint32_t header[4] = {
        static_cast<uint32_t>(pending.size()),
        static_cast<uint32_t>(payload.size()),
        crc32_buf(reinterpret_cast<const uint8_t*>(payload.data()),
                  payload.size()),
        0u};
    if (fwrite(header, sizeof(header), 1, f) != 1) return false;
    if (!lens.empty() &&
        fwrite(lens.data(), sizeof(uint32_t), lens.size(), f) != lens.size())
      return false;
    if (!payload.empty() &&
        fwrite(payload.data(), 1, payload.size(), f) != payload.size())
      return false;
    pending.clear();
    pending_bytes = 0;
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<std::string> chunk;  // records of current chunk
  size_t next_idx = 0;
  bool error = false;

  bool load_chunk() {
    uint32_t header[4];
    if (fread(header, sizeof(header), 1, f) != 1) return false;  // EOF
    uint32_t n = header[0], payload_len = header[1], crc = header[2];
    std::vector<uint32_t> lens(n);
    if (n && fread(lens.data(), sizeof(uint32_t), n, f) != n) {
      error = true;
      return false;
    }
    std::string payload(payload_len, '\0');
    if (payload_len &&
        fread(&payload[0], 1, payload_len, f) != payload_len) {
      error = true;
      return false;
    }
    if (crc32_buf(reinterpret_cast<const uint8_t*>(payload.data()),
                  payload.size()) != crc) {
      error = true;
      return false;
    }
    chunk.clear();
    size_t off = 0;
    for (uint32_t i = 0; i < n; i++) {
      chunk.emplace_back(payload.substr(off, lens[i]));
      off += lens[i];
    }
    next_idx = 0;
    return true;
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, int max_chunk_records,
                      long max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  if (fwrite(kMagic, sizeof(kMagic), 1, f) != 1) {
    fclose(f);
    return nullptr;
  }
  auto* w = new Writer();
  w->f = f;
  w->max_chunk_records =
      max_chunk_records > 0 ? static_cast<size_t>(max_chunk_records) : 1000;
  w->max_chunk_bytes =
      max_chunk_bytes > 0 ? static_cast<size_t>(max_chunk_bytes)
                          : (32u << 20);
  return w;
}

int rio_writer_write(void* handle, const uint8_t* buf, long len) {
  auto* w = static_cast<Writer*>(handle);
  w->pending.emplace_back(reinterpret_cast<const char*>(buf),
                          static_cast<size_t>(len));
  w->pending_bytes += static_cast<size_t>(len);
  if (w->pending.size() >= w->max_chunk_records ||
      w->pending_bytes >= w->max_chunk_bytes) {
    return w->flush_chunk() ? 0 : -1;
  }
  return 0;
}

int rio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  int rc = w->flush_chunk() ? 0 : -1;
  fclose(w->f);
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  char magic[8];
  if (fread(magic, sizeof(magic), 1, f) != 1 ||
      memcmp(magic, kMagic, sizeof(kMagic)) != 0) {
    fclose(f);
    return nullptr;
  }
  auto* s = new Scanner();
  s->f = f;
  return s;
}

// Returns record length (>=0) and sets *out to a malloc'd buffer the caller
// frees with rio_free; returns -1 at EOF, -2 on corruption.
long rio_scanner_next(void* handle, uint8_t** out) {
  auto* s = static_cast<Scanner*>(handle);
  if (s->next_idx >= s->chunk.size()) {
    if (!s->load_chunk()) return s->error ? -2 : -1;
  }
  const std::string& rec = s->chunk[s->next_idx++];
  auto* buf = static_cast<uint8_t*>(malloc(rec.size() ? rec.size() : 1));
  memcpy(buf, rec.data(), rec.size());
  *out = buf;
  return static_cast<long>(rec.size());
}

void rio_free(uint8_t* buf) { free(buf); }

void rio_scanner_close(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
