// Tensor binary serialization: the save/load op wire-and-disk format.
//
// Reference analogue: operators/save_op.cc / load_op.cc +
// framework/lod_tensor.cc SerializeToStream (version header u32, dtype,
// dims, raw data, then LoD levels). Re-designed: one self-describing record
//   u32 version | u32 dtype_code | u32 ndim | u64 dims[ndim]
//   u64 nbytes  | raw data
//   u32 lod_levels | per level: u64 n | u64 offsets[n]
// Used by the C++ recordio data path and the checkpoint code; Python side
// reads/writes the same format via ctypes (native/__init__.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {
constexpr uint32_t kVersion = 1;
}

extern "C" {

// Serialize into a malloc'd buffer (caller frees with ts_free); returns
// total length.
long ts_serialize(uint32_t dtype_code, const uint64_t* dims, uint32_t ndim,
                  const uint8_t* data, uint64_t nbytes,
                  const uint64_t* lod_lens, uint32_t lod_levels,
                  const uint64_t* lod_flat, uint8_t** out) {
  size_t lod_elems = 0;
  for (uint32_t i = 0; i < lod_levels; i++) lod_elems += lod_lens[i];
  size_t total = 4 + 4 + 4 + 8ull * ndim + 8 + nbytes + 4 +
                 lod_levels * 8ull + lod_elems * 8ull;
  auto* buf = static_cast<uint8_t*>(malloc(total ? total : 1));
  if (!buf) return -1;
  uint8_t* p = buf;
  auto put32 = [&p](uint32_t v) { memcpy(p, &v, 4); p += 4; };
  auto put64 = [&p](uint64_t v) { memcpy(p, &v, 8); p += 8; };
  put32(kVersion);
  put32(dtype_code);
  put32(ndim);
  for (uint32_t i = 0; i < ndim; i++) put64(dims[i]);
  put64(nbytes);
  memcpy(p, data, nbytes);
  p += nbytes;
  put32(lod_levels);
  size_t off = 0;
  for (uint32_t i = 0; i < lod_levels; i++) {
    put64(lod_lens[i]);
    for (uint64_t j = 0; j < lod_lens[i]; j++) put64(lod_flat[off + j]);
    off += lod_lens[i];
  }
  *out = buf;
  return static_cast<long>(total);
}

// Parse header: fills dtype_code, ndim, dims (caller provides space for 16),
// nbytes, data_offset. Returns 0 or -1 on malformed input.
int ts_parse_header(const uint8_t* buf, long len, uint32_t* dtype_code,
                    uint32_t* ndim, uint64_t* dims, uint64_t* nbytes,
                    uint64_t* data_offset) {
  if (len < 12) return -1;
  const uint8_t* p = buf;
  uint32_t version;
  memcpy(&version, p, 4);
  p += 4;
  if (version != kVersion) return -1;
  memcpy(dtype_code, p, 4);
  p += 4;
  memcpy(ndim, p, 4);
  p += 4;
  if (*ndim > 16 || len < 12 + 8l * (*ndim) + 8) return -1;
  for (uint32_t i = 0; i < *ndim; i++) {
    memcpy(&dims[i], p, 8);
    p += 8;
  }
  memcpy(nbytes, p, 8);
  p += 8;
  *data_offset = static_cast<uint64_t>(p - buf);
  if (static_cast<uint64_t>(len) < *data_offset + *nbytes) return -1;
  return 0;
}

void ts_free(uint8_t* buf) { free(buf); }

}  // extern "C"
