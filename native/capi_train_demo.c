/* Pure-C training client of the pd_capi API — the proof that a C
 * application can TRAIN from a paddle_tpu save_aot_trainer artifact
 * with no Python of its own (reference analogue: the C++ train/demo,
 * paddle/fluid/train/demo/demo_trainer.cc, which drives
 * framework::Executor from a saved program).
 *
 * Usage: capi_train_demo <artifact_dir> <steps> <batch> <feat> <ckpt_dir>
 * Model contract: two feeds in export order — "x" [batch, feat]
 * float32 then "y" [batch, 1] float32 — one scalar loss fetch (the
 * shape the Python test exports). Feeds deterministic synthetic data, prints
 * "loss <step> <value>" per step, checkpoints into <ckpt_dir>, reopens
 * the checkpoint, runs the remaining steps, and prints the resumed
 * losses — the Python test asserts both halves match an in-process
 * AotTrainer trajectory exactly.
 */
#include <stdio.h>
#include <stdlib.h>

#include "pd_capi.h"

static void fill_batch(float *x, float *y, int64_t batch, int64_t feat,
                       int step) {
  for (int64_t i = 0; i < batch * feat; ++i)
    x[i] = ((float)(((i + 13 * step) * 37) % 65) - 32.0f) / 32.0f;
  for (int64_t i = 0; i < batch; ++i)
    y[i] = ((float)(((i + 7 * step) * 29) % 33) - 16.0f) / 16.0f;
}

static int run_steps(void *tr, int from, int to, int64_t batch,
                     int64_t feat, float *x, float *y) {
  pd_tensor in[2];
  for (int step = from; step < to; ++step) {
    fill_batch(x, y, batch, feat, step);
    in[0].dtype = PD_FLOAT32;
    in[0].ndim = 2;
    in[0].dims[0] = batch;
    in[0].dims[1] = feat;
    in[0].data = x;
    in[0].nbytes = (size_t)(batch * feat) * sizeof(float);
    in[0].name[0] = '\0'; /* positional: the artifact's export order */
    in[1] = in[0];
    in[1].dims[1] = 1;
    in[1].data = y;
    in[1].nbytes = (size_t)batch * sizeof(float);

    pd_tensor out[4];
    int n = pd_trainer_step(tr, in, 2, out, 4);
    if (n < 0) {
      fprintf(stderr, "step failed: %s\n", pd_last_error());
      return -1;
    }
    if (n < 1 || out[0].nbytes < sizeof(float)) {
      fprintf(stderr, "expected a scalar loss fetch\n");
      return -1;
    }
    printf("loss %d %.6f\n", step, *(const float *)out[0].data);
    for (int i = 0; i < n && i < 4; ++i) pd_free_tensor_data(&out[i]);
  }
  return 0;
}

int main(int argc, char **argv) {
  if (argc != 6) {
    fprintf(stderr,
            "usage: %s <artifact_dir> <steps> <batch> <feat> <ckpt_dir>\n",
            argv[0]);
    return 2;
  }
  const char *artifact = argv[1];
  int steps = atoi(argv[2]);
  int64_t batch = atoll(argv[3]);
  int64_t feat = atoll(argv[4]);
  const char *ckpt = argv[5];
  int half = steps / 2;

  float *x = (float *)malloc((size_t)(batch * feat) * sizeof(float));
  float *y = (float *)malloc((size_t)batch * sizeof(float));
  if (!x || !y) return 1;

  void *tr = pd_create_trainer(artifact);
  if (!tr) {
    fprintf(stderr, "create failed: %s\n", pd_last_error());
    return 1;
  }
  if (run_steps(tr, 0, half, batch, feat, x, y) != 0) return 1;
  if (pd_trainer_save(tr, ckpt) != 0) {
    fprintf(stderr, "save failed: %s\n", pd_last_error());
    return 1;
  }
  pd_destroy_trainer(tr);

  /* resume from the checkpoint in a fresh handle */
  tr = pd_create_trainer(ckpt);
  if (!tr) {
    fprintf(stderr, "reopen failed: %s\n", pd_last_error());
    return 1;
  }
  printf("resumed\n");
  if (run_steps(tr, half, steps, batch, feat, x, y) != 0) return 1;
  pd_destroy_trainer(tr);
  free(x);
  free(y);
  printf("CAPI-TRAIN-OK\n");
  return 0;
}
