/* Pure-C client of the pd_capi inference API — the proof that a C
 * application can serve a paddle_tpu save_aot artifact with no Python
 * of its own (reference analogue: the legacy capi examples under
 * paddle/legacy/capi/examples/model_inference).
 *
 * Usage: capi_demo <aot_model_dir> <batch> <c> <h> <w>
 * Feeds a deterministic [batch, c, h, w] float32 image and prints each
 * output as: name, dims, then every value at %.6f — the Python test
 * parses this and compares against AotPredictor.run in-process.
 */
#include <stdio.h>
#include <stdlib.h>

#include "pd_capi.h"

int main(int argc, char **argv) {
  if (argc != 6) {
    fprintf(stderr, "usage: %s <model_dir> <batch> <c> <h> <w>\n", argv[0]);
    return 2;
  }
  const char *model_dir = argv[1];
  int64_t dims[4];
  size_t count = 1;
  for (int i = 0; i < 4; ++i) {
    dims[i] = atoll(argv[2 + i]);
    count *= (size_t)dims[i];
  }

  void *pred = pd_create_predictor(model_dir);
  if (!pred) {
    fprintf(stderr, "create failed: %s\n", pd_last_error());
    return 1;
  }

  float *img = (float *)malloc(count * sizeof(float));
  for (size_t i = 0; i < count; ++i)
    img[i] = ((float)((i * 37) % 65) - 32.0f) / 32.0f; /* [-1, 1) */

  pd_tensor in = {0};
  in.dtype = PD_FLOAT32;
  in.ndim = 4;
  for (int i = 0; i < 4; ++i) in.dims[i] = dims[i];
  in.data = img;
  in.nbytes = count * sizeof(float);
  /* name left empty: positional feed order */

  pd_tensor outs[8];
  int n = pd_predictor_run(pred, &in, 1, outs, 8);
  if (n < 0) {
    fprintf(stderr, "run failed: %s\n", pd_last_error());
    return 1;
  }
  printf("n_out %d\n", n);
  for (int i = 0; i < n && i < 8; ++i) {
    printf("out %s ndim %d dims", outs[i].name, outs[i].ndim);
    size_t total = 1;
    for (int d = 0; d < outs[i].ndim; ++d) {
      printf(" %lld", (long long)outs[i].dims[d]);
      total *= (size_t)outs[i].dims[d];
    }
    printf("\n");
    const float *v = (const float *)outs[i].data;
    for (size_t j = 0; j < total; ++j) printf("%.6f ", (double)v[j]);
    printf("\n");
    pd_free_tensor_data(&outs[i]);
  }

  /* second run on the same handle: the jit cache must be warm */
  n = pd_predictor_run(pred, &in, 1, outs, 8);
  if (n < 0) {
    fprintf(stderr, "second run failed: %s\n", pd_last_error());
    return 1;
  }
  for (int i = 0; i < n && i < 8; ++i) pd_free_tensor_data(&outs[i]);
  printf("second run ok\n");

  free(img);
  pd_destroy_predictor(pred);
  printf("CAPI-DEMO-OK\n");
  return 0;
}
