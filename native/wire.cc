// Typed wire codec for the host-side RPC / elastic / snapshot paths.
//
// Reference analogue: operators/distributed/grpc_serde.cc +
// send_recv.proto.in (VariableMessage) — the reference serializes
// LoDTensor/SelectedRows straight into gRPC ByteBuffers with a typed
// header instead of trusting arbitrary payloads. Redesigned here as a
// self-describing recursive value format (the message set is richer than
// VariableMessage: task-queue payloads, barrier acks, checkpoint meta),
// with the decoder as the security boundary: every offset/length/depth is
// validated in C++ before Python sees a byte, so a malformed or hostile
// frame yields a clean parse error — never code execution (this replaces
// the round-3 pickle.loads on sockets).
//
// Frame:  u32 magic 'PTW1' | u32 version | value
// value:  u8 tag | payload
//   0 NONE | 1 BOOL u8 | 2 INT i64 | 3 FLOAT f64
//   4 STR  u32 len + utf8        | 5 BYTES u32 len + raw
//   6 LIST u32 n + n values      | 7 TUPLE u32 n + n values
//   8 DICT u32 n + n * (u32 klen + key + value)
//   9 TENSOR u32 dtype | u32 ndim | u64 dims[ndim] | u64 nbytes | raw
//
// Builder writes counts up front (caller supplies them), so encoding is a
// single append pass; the parser re-validates counts against the actual
// byte stream. Parsed nodes reference payload bytes by offset into the
// caller's buffer — zero-copy for tensor/bytes payloads.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>
#include <string>
#include <utility>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x31575450;  // "PTW1"
constexpr uint32_t kVersion = 1;
constexpr uint32_t kMaxDepth = 64;
constexpr uint32_t kMaxNdim = 8;
constexpr uint64_t kMaxNodes = 1u << 22;  // 4M nodes: DoS guard

enum Tag : uint8_t {
  kNone = 0,
  kBool = 1,
  kInt = 2,
  kFloat = 3,
  kStr = 4,
  kBytes = 5,
  kList = 6,
  kTuple = 7,
  kDict = 8,
  kTensor = 9,
};

struct Builder {
  std::vector<uint8_t> buf;
  void put8(uint8_t v) { buf.push_back(v); }
  void put32(uint32_t v) {
    size_t n = buf.size();
    buf.resize(n + 4);
    memcpy(buf.data() + n, &v, 4);
  }
  void put64(uint64_t v) {
    size_t n = buf.size();
    buf.resize(n + 8);
    memcpy(buf.data() + n, &v, 8);
  }
  void raw(const uint8_t* p, uint64_t n) {
    size_t at = buf.size();
    buf.resize(at + n);
    if (n) memcpy(buf.data() + at, p, n);
  }
};

struct Node {
  uint8_t tag = kNone;
  int64_t ival = 0;
  double fval = 0;
  uint64_t off = 0;    // STR/BYTES/TENSOR payload offset in frame
  uint64_t len = 0;    // payload byte length
  uint32_t dtype = 0;  // TENSOR
  uint32_t ndim = 0;
  uint64_t dims[kMaxNdim] = {0};
  uint32_t count = 0;       // LIST/TUPLE/DICT children
  uint32_t child_base = 0;  // index into Parsed::children
};

struct Parsed {
  std::vector<Node> nodes;
  std::vector<uint32_t> children;
  // dict keys aligned with children slots (off,len into frame)
  std::vector<std::pair<uint64_t, uint32_t>> keys;
};

struct Cursor {
  const uint8_t* buf;
  uint64_t len;
  uint64_t pos = 0;
  bool need(uint64_t n) const { return len - pos >= n && pos + n >= pos; }
  bool get8(uint8_t* v) {
    if (!need(1)) return false;
    *v = buf[pos++];
    return true;
  }
  bool get32(uint32_t* v) {
    if (!need(4)) return false;
    memcpy(v, buf + pos, 4);
    pos += 4;
    return true;
  }
  bool get64(uint64_t* v) {
    if (!need(8)) return false;
    memcpy(v, buf + pos, 8);
    pos += 8;
    return true;
  }
};

// Recursive-descent parse; returns node index or -1 on malformed input.
long parse_value(Parsed* out, Cursor* c, uint32_t depth) {
  if (depth > kMaxDepth || out->nodes.size() >= kMaxNodes) return -1;
  uint8_t tag;
  if (!c->get8(&tag)) return -1;
  long idx = static_cast<long>(out->nodes.size());
  out->nodes.emplace_back();
  out->nodes[idx].tag = tag;
  switch (tag) {
    case kNone:
      return idx;
    case kBool: {
      uint8_t v;
      if (!c->get8(&v) || v > 1) return -1;
      out->nodes[idx].ival = v;
      return idx;
    }
    case kInt: {
      uint64_t v;
      if (!c->get64(&v)) return -1;
      memcpy(&out->nodes[idx].ival, &v, 8);
      return idx;
    }
    case kFloat: {
      uint64_t v;
      if (!c->get64(&v)) return -1;
      memcpy(&out->nodes[idx].fval, &v, 8);
      return idx;
    }
    case kStr:
    case kBytes: {
      uint32_t n;
      if (!c->get32(&n) || !c->need(n)) return -1;
      out->nodes[idx].off = c->pos;
      out->nodes[idx].len = n;
      c->pos += n;
      return idx;
    }
    case kList:
    case kTuple: {
      uint32_t n;
      if (!c->get32(&n)) return -1;
      // every element needs >=1 byte: a count beyond the remaining bytes
      // is a lie — reject before reserving anything (hostile counts must
      // not become multi-GB allocations)
      if (n > c->len - c->pos) return -1;
      out->nodes[idx].count = n;
      std::vector<uint32_t> kids;
      kids.reserve(n);
      for (uint32_t i = 0; i < n; i++) {
        long k = parse_value(out, c, depth + 1);
        if (k < 0) return -1;
        kids.push_back(static_cast<uint32_t>(k));
      }
      out->nodes[idx].child_base = static_cast<uint32_t>(
          out->children.size());
      for (uint32_t k : kids) {
        out->children.push_back(k);
        out->keys.emplace_back(0, 0);
      }
      return idx;
    }
    case kDict: {
      uint32_t n;
      if (!c->get32(&n)) return -1;
      // each entry needs >=5 bytes (u32 klen + value tag)
      if (n > (c->len - c->pos) / 5) return -1;
      out->nodes[idx].count = n;
      std::vector<uint32_t> kids;
      std::vector<std::pair<uint64_t, uint32_t>> ks;
      kids.reserve(n);
      ks.reserve(n);
      for (uint32_t i = 0; i < n; i++) {
        uint32_t klen;
        if (!c->get32(&klen) || !c->need(klen)) return -1;
        ks.emplace_back(c->pos, klen);
        c->pos += klen;
        long k = parse_value(out, c, depth + 1);
        if (k < 0) return -1;
        kids.push_back(static_cast<uint32_t>(k));
      }
      out->nodes[idx].child_base = static_cast<uint32_t>(
          out->children.size());
      for (uint32_t i = 0; i < n; i++) {
        out->children.push_back(kids[i]);
        out->keys.push_back(ks[i]);
      }
      return idx;
    }
    case kTensor: {
      Node& nd = out->nodes[idx];
      uint64_t nbytes;
      if (!c->get32(&nd.dtype) || !c->get32(&nd.ndim)) return -1;
      if (nd.ndim > kMaxNdim) return -1;
      uint64_t elems = 1;
      for (uint32_t i = 0; i < nd.ndim; i++) {
        if (!c->get64(&nd.dims[i])) return -1;
        // overflow-guarded element count (dims are attacker-controlled)
        if (nd.dims[i] && elems > UINT64_MAX / nd.dims[i]) return -1;
        elems *= nd.dims[i];
      }
      if (!c->get64(&nbytes) || !c->need(nbytes)) return -1;
      nd.off = c->pos;
      nd.len = nbytes;
      c->pos += nbytes;
      return idx;
    }
    default:
      return -1;
  }
}

}  // namespace

extern "C" {

// ---- builder ----
void* wirb_new() { return new (std::nothrow) Builder(); }

void wirb_none(void* h) { static_cast<Builder*>(h)->put8(kNone); }

void wirb_bool(void* h, int v) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kBool);
  b->put8(v ? 1 : 0);
}

void wirb_int(void* h, int64_t v) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kInt);
  uint64_t u;
  memcpy(&u, &v, 8);
  b->put64(u);
}

void wirb_float(void* h, double v) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kFloat);
  uint64_t u;
  memcpy(&u, &v, 8);
  b->put64(u);
}

void wirb_str(void* h, const uint8_t* p, uint32_t n) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kStr);
  b->put32(n);
  b->raw(p, n);
}

void wirb_bytes(void* h, const uint8_t* p, uint32_t n) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kBytes);
  b->put32(n);
  b->raw(p, n);
}

void wirb_list(void* h, uint32_t n) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kList);
  b->put32(n);
}

void wirb_tuple(void* h, uint32_t n) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kTuple);
  b->put32(n);
}

void wirb_dict(void* h, uint32_t n) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kDict);
  b->put32(n);
}

void wirb_key(void* h, const uint8_t* p, uint32_t n) {
  auto* b = static_cast<Builder*>(h);
  b->put32(n);
  b->raw(p, n);
}

void wirb_tensor(void* h, uint32_t dtype, const uint64_t* dims,
                 uint32_t ndim, const uint8_t* data, uint64_t nbytes) {
  auto* b = static_cast<Builder*>(h);
  b->put8(kTensor);
  b->put32(dtype);
  b->put32(ndim);
  for (uint32_t i = 0; i < ndim; i++) b->put64(dims[i]);
  b->put64(nbytes);
  b->raw(data, nbytes);
}

// Prepend magic+version, hand over a malloc'd copy, destroy the builder.
long wirb_finish(void* h, uint8_t** out) {
  auto* b = static_cast<Builder*>(h);
  size_t total = 8 + b->buf.size();
  auto* frame = static_cast<uint8_t*>(malloc(total));
  if (!frame) {
    delete b;
    return -1;
  }
  memcpy(frame, &kMagic, 4);
  memcpy(frame + 4, &kVersion, 4);
  memcpy(frame + 8, b->buf.data(), b->buf.size());
  delete b;
  *out = frame;
  return static_cast<long>(total);
}

void wirb_abort(void* h) { delete static_cast<Builder*>(h); }

void wire_free(uint8_t* p) { free(p); }

// ---- parser ----
// Validates the whole frame; returns a handle or NULL on malformed input.
// The handle references `buf` by offset only — the caller must keep the
// buffer alive while reading.
void* wirp_new(const uint8_t* buf, long len) {
  if (len < 9) return nullptr;
  uint32_t magic, version;
  memcpy(&magic, buf, 4);
  memcpy(&version, buf + 4, 4);
  if (magic != kMagic || version != kVersion) return nullptr;
  auto* p = new (std::nothrow) Parsed();
  if (!p) return nullptr;
  Cursor c{buf, static_cast<uint64_t>(len), 8};
  long root;
  try {
    root = parse_value(p, &c, 0);
  } catch (const std::bad_alloc&) {
    // allocation pressure from a hostile frame must not escape the C ABI
    delete p;
    return nullptr;
  }
  if (root != 0 || c.pos != c.len) {  // root must be node 0, no trailing junk
    delete p;
    return nullptr;
  }
  return p;
}

int wirp_tag(void* h, uint32_t idx) {
  auto* p = static_cast<Parsed*>(h);
  if (idx >= p->nodes.size()) return -1;
  return p->nodes[idx].tag;
}

int wirp_int(void* h, uint32_t idx, int64_t* out) {
  auto* p = static_cast<Parsed*>(h);
  if (idx >= p->nodes.size()) return -1;
  const Node& n = p->nodes[idx];
  if (n.tag != kInt && n.tag != kBool) return -1;
  *out = n.ival;
  return 0;
}

int wirp_float(void* h, uint32_t idx, double* out) {
  auto* p = static_cast<Parsed*>(h);
  if (idx >= p->nodes.size()) return -1;
  if (p->nodes[idx].tag != kFloat) return -1;
  *out = p->nodes[idx].fval;
  return 0;
}

int wirp_payload(void* h, uint32_t idx, uint64_t* off, uint64_t* len) {
  auto* p = static_cast<Parsed*>(h);
  if (idx >= p->nodes.size()) return -1;
  const Node& n = p->nodes[idx];
  if (n.tag != kStr && n.tag != kBytes) return -1;
  *off = n.off;
  *len = n.len;
  return 0;
}

long wirp_count(void* h, uint32_t idx) {
  auto* p = static_cast<Parsed*>(h);
  if (idx >= p->nodes.size()) return -1;
  const Node& n = p->nodes[idx];
  if (n.tag != kList && n.tag != kTuple && n.tag != kDict) return -1;
  return n.count;
}

long wirp_child(void* h, uint32_t idx, uint32_t i) {
  auto* p = static_cast<Parsed*>(h);
  if (idx >= p->nodes.size()) return -1;
  const Node& n = p->nodes[idx];
  if ((n.tag != kList && n.tag != kTuple && n.tag != kDict) ||
      i >= n.count) {
    return -1;
  }
  return p->children[n.child_base + i];
}

int wirp_key(void* h, uint32_t idx, uint32_t i, uint64_t* off,
             uint32_t* len) {
  auto* p = static_cast<Parsed*>(h);
  if (idx >= p->nodes.size()) return -1;
  const Node& n = p->nodes[idx];
  if (n.tag != kDict || i >= n.count) return -1;
  *off = p->keys[n.child_base + i].first;
  *len = p->keys[n.child_base + i].second;
  return 0;
}

int wirp_tensor(void* h, uint32_t idx, uint32_t* dtype, uint32_t* ndim,
                uint64_t* dims /* space for 8 */, uint64_t* off,
                uint64_t* nbytes) {
  auto* p = static_cast<Parsed*>(h);
  if (idx >= p->nodes.size()) return -1;
  const Node& n = p->nodes[idx];
  if (n.tag != kTensor) return -1;
  *dtype = n.dtype;
  *ndim = n.ndim;
  for (uint32_t i = 0; i < n.ndim; i++) dims[i] = n.dims[i];
  *off = n.off;
  *nbytes = n.len;
  return 0;
}

void wirp_free(void* h) { delete static_cast<Parsed*>(h); }

}  // extern "C"
